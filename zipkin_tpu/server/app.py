"""The HTTP server: Zipkin v2 API, collectors, health, and metrics.

Reference semantics: ``zipkin-server`` (SURVEY.md §2.4) — the Armeria app
rebuilt on aiohttp. Route-for-route:

- ``POST /api/v2/spans`` and ``POST /api/v1/spans`` (+gzip, content-type or
  first-byte format sniffing)   [``ZipkinHttpCollector.java``]
- ``GET /api/v2/{traces,trace/{id},traceMany,services,spans,remoteServices,
  dependencies,autocompleteKeys,autocompleteValues}``
  [``ZipkinQueryApiV2.java``]
- ``GET /health`` aggregating ``Component.check()``
  [``ZipkinHealthController.java``]
- ``GET /metrics`` (actuator counter names kept verbatim) and
  ``GET /prometheus``
- ``GET /config.json`` (UI config), ``GET /info``

Ingest responds 202 as soon as the storage call is dispatched, mirroring
the reference's enqueue-then-ack behavior.
"""

from __future__ import annotations

import asyncio
import gzip
import json
import logging
import time
from typing import Dict, List, Optional, Tuple

from aiohttp import web

import zipkin_tpu
from zipkin_tpu import obs
from zipkin_tpu.collector.core import (
    Collector,
    CollectorSampler,
    InMemoryCollectorMetrics,
)
from zipkin_tpu.internal.hex import normalize_trace_id
from zipkin_tpu.model import codec, json_v2
from zipkin_tpu.obs import critpath
from zipkin_tpu.model.codec import Encoding
from zipkin_tpu.runtime.tenant import (
    CURRENT_TENANT,
    TENANT_HEADER,
    normalize_tenant,
)
from zipkin_tpu.server.config import ServerConfig
from zipkin_tpu.storage.memory import InMemoryStorage
from zipkin_tpu.storage.spi import QueryRequest, StorageComponent
from zipkin_tpu.storage.throttle import RejectedExecutionError
from zipkin_tpu.tpu.mp_ingest import IngestBackpressure
from zipkin_tpu.utils.component import Component

logger = logging.getLogger(__name__)

JSON = "application/json"


class PayloadTooLarge(ValueError):
    """Inflated request body exceeded the decompression cap."""


def build_storage(config: ServerConfig) -> StorageComponent:
    """STORAGE_TYPE -> StorageComponent, the autoconfig seam."""
    common = dict(
        strict_trace_id=config.strict_trace_id,
        search_enabled=config.search_enabled,
        autocomplete_keys=config.autocomplete_keys,
    )
    if config.storage_type == "mem":
        return InMemoryStorage(max_span_count=config.mem_max_spans, **common)
    if config.storage_type == "tpu":
        from zipkin_tpu.storage.tpu import TpuStorage
        from zipkin_tpu.tpu.state import AggConfig

        agg_kwargs = dict(config.tpu_agg)
        if config.tpu_sampling:
            # sampling is a STATIC AggConfig field (it changes the
            # compiled ingest step), so it rides the agg config rather
            # than a storage kwarg
            agg_kwargs["sampling"] = True
            agg_kwargs["sample_rare_min"] = config.tpu_sampling_rare_min

        def _make(archive_dir):
            return TpuStorage(
                max_span_count=config.mem_max_spans,
                batch_size=config.tpu_batch_size,
                num_devices=config.tpu_devices,
                checkpoint_dir=config.tpu_checkpoint_dir,
                wal_dir=config.tpu_wal_dir,
                wal_fsync=config.tpu_wal_fsync,
                archive_dir=archive_dir,
                archive_max_bytes=config.tpu_archive_max_bytes,
                archive_segment_bytes=config.tpu_archive_segment_bytes,
                config=AggConfig(**agg_kwargs) if agg_kwargs else None,
                fast_archive_sample=config.tpu_fast_archive_sample,
                sampling_budget=(
                    config.tpu_sampling_budget if config.tpu_sampling else 0.0
                ),
                sampling_interval_s=config.tpu_sampling_interval_s,
                sampling_min_rate=config.tpu_sampling_min_rate,
                sampling_tail_quantile=config.tpu_sampling_tail_quantile,
                snapshot_keep=config.tpu_snapshot_keep,
                scrub_interval_s=config.tpu_scrub_interval_s,
                scrub_bytes_per_sec=config.tpu_scrub_bytes_per_sec,
                mirror_segment_bytes=config.tpu_mirror_segment_bytes,
                mirror_segment_readers=config.tpu_readers,
                **common,
            )

        if config.tpu_archive_dir:
            logger.info(
                "span archive: %s (budget %d bytes)",
                config.tpu_archive_dir, config.tpu_archive_max_bytes,
            )
            try:
                return _make(config.tpu_archive_dir)
            except OSError as e:
                # the default-on archive must not brick a server whose
                # cwd is read-only: degrade to archive-free (the r3
                # posture) loudly instead of refusing to boot
                logger.warning(
                    "span archive dir %s unusable (%s); serving without "
                    "the disk archive", config.tpu_archive_dir, e,
                )
        return _make(None)
    raise ValueError(f"unknown STORAGE_TYPE: {config.storage_type}")


class ZipkinServer:
    """Wires storage + collector + routes; owns component lifecycle."""

    def __init__(
        self,
        config: Optional[ServerConfig] = None,
        *,
        storage: Optional[StorageComponent] = None,
    ) -> None:
        self.config = config or ServerConfig()
        self.storage = storage if storage is not None else build_storage(self.config)
        if self.config.throttle_enabled:
            from zipkin_tpu.storage.throttle import ThrottledStorage

            self.storage = ThrottledStorage(
                self.storage, max_concurrency=self.config.throttle_max_concurrency
            )
        self.metrics = InMemoryCollectorMetrics()
        sampler = CollectorSampler(self.config.sample_rate)
        http_metrics = self.metrics.for_transport("http")
        self._mp_ingester = None
        if self.config.tpu_mp_workers > 0:
            from zipkin_tpu import native
            from zipkin_tpu.tpu.store import TpuStorage as _CoreTpu

            # the MP tier needs the CORE store (it reaches the vocab and
            # aggregator directly); a throttle wrapper still exposes it
            # via .delegate
            core = getattr(self.storage, "delegate", self.storage)
            if (
                isinstance(core, _CoreTpu)
                and native.available()
                and self.config.tpu_fast_ingest
            ):
                from zipkin_tpu.tpu.mp_ingest import MultiProcessIngester

                self._mp_ingester = MultiProcessIngester(
                    core,
                    workers=self.config.tpu_mp_workers,
                    sampler=sampler,
                    queue_depth=self.config.tpu_mp_queue_depth,
                    ring_slots=self.config.tpu_mp_ring_slots,
                    coalesce_max=self.config.tpu_mp_coalesce_max,
                    metrics=http_metrics,
                    # ingest critical-path tracer (ISSUE 11): size the
                    # shared-memory interval ledger; 0 disables tracing
                    critpath_slots=(
                        self.config.obs_critpath_slots
                        if self.config.obs_critpath_enabled
                        else 0
                    ),
                    critpath_reclaim_s=self.config.obs_critpath_reclaim_s,
                )
                # surface the tier's gauges on ingest_counters() —
                # /metrics, /prometheus and /statusz all read it — and
                # let the storage adapter drain/close an attached tier
                # if the server's stop() never ran
                core.mp_ingester = self._mp_ingester
            else:
                logger.warning(
                    "TPU_MP_WORKERS=%d ignored: requires STORAGE_TYPE=tpu, "
                    "the native codec, and TPU_FAST_INGEST=true (the MP "
                    "tier is the fast path's scale-out)",
                    self.config.tpu_mp_workers,
                )
        self.collector = Collector(
            self.storage,
            sampler=sampler,
            metrics=http_metrics,
            fast_ingest=self.config.tpu_fast_ingest,
            mp_ingester=self._mp_ingester,
        )
        self._obs_emitter = None
        if self.config.obs_selfspans_enabled:
            from zipkin_tpu.obs.selfspans import SelfSpanEmitter

            # over-budget pipeline stages publish slow-dispatch spans
            # (service zipkin-tpu-pipeline) through the ordinary object
            # path — the tracer dogfooding itself
            self._obs_emitter = SelfSpanEmitter(
                Collector(
                    self.storage,
                    metrics=self.metrics.for_transport("obs"),
                ),
                budget_scale=self.config.obs_budget_scale,
            )
            self._obs_emitter.install(obs.RECORDER)
        # slowest-chunk critpath timelines ride the self-span plane when
        # both are armed: the stitcher hands pre-built spans to the
        # emitter's suppressed drain thread
        if (
            self._mp_ingester is not None
            and getattr(self._mp_ingester, "critpath", None) is not None
            and self._obs_emitter is not None
        ):
            self._mp_ingester.critpath.emitter = self._obs_emitter
        # query-plane observatory (obs/querytrace.py, ISSUE 12): the
        # store owns the stitcher + the instrumented aggregator lock;
        # propagate the configured enablement (trace arming and the lock
        # ledger switch together) and give the slowest-query timeline
        # the same self-span plane the critpath stitcher rides.
        _qt_core = getattr(self.storage, "delegate", self.storage)
        self._querytrace = getattr(_qt_core, "querytrace", None)
        if hasattr(_qt_core, "set_query_observatory"):
            _qt_core.set_query_observatory(self.config.obs_query_enabled)
        if self._querytrace is not None and self._obs_emitter is not None:
            self._querytrace.emitter = self._obs_emitter
        # epoch-published read mirror (tpu/mirror.py, ISSUE 14): apply
        # the configured posture to the store's mirror before any ticker
        # or route can consult it. TPU_READ_MIRROR=false reverts every
        # query entrypoint to the locked read path; the max-stale knob
        # is the published staleness contract the query_mirror_staleness
        # SLO pages against.
        self._mirror = getattr(_qt_core, "mirror", None)
        if self._mirror is not None:
            self._mirror.enabled = bool(self.config.tpu_read_mirror)
            self._mirror.max_stale_ms = float(
                self.config.tpu_mirror_max_stale_ms
            )
        # windowed telemetry plane + SLO watchdog (ISSUE 9): per-tick
        # delta rings over the recorder/counters, burn-rate evaluation
        # on every tick. The ticker thread follows start()/stop();
        # read paths catch up lazily so un-started embedders work too.
        self._obs_windows = None
        self._obs_slo = None
        self._obs_shadow = None
        self._accuracy = None
        self._obs_incidents = None
        if self.config.obs_windows_enabled:
            from zipkin_tpu.obs.windows import WindowedTelemetry

            self._obs_windows = WindowedTelemetry(
                obs.RECORDER,
                self._window_counter_source,
                tick_s=self.config.obs_windows_tick_s,
            )
            # accuracy observatory (ISSUE 10): bounded host shadow of the
            # ingest stream + rollup-cadence relative-error estimators.
            # TPU storage only (it audits the device sketch plane) and
            # riding the windowed ticker; registered BEFORE the watchdog
            # so each tick rolls up before burn evaluation (the gauges
            # the watchdog reads are the tick's captured counters, so
            # alerts lag at most one tick).
            core = getattr(self.storage, "delegate", self.storage)
            if (
                self.config.obs_shadow_enabled
                and hasattr(core, "agg")
                and hasattr(core, "vocab")
            ):
                from zipkin_tpu.obs.accuracy import AccuracyEstimator
                from zipkin_tpu.obs.shadow import HostShadow

                self._obs_shadow = HostShadow(
                    reservoir_k=self.config.obs_shadow_reservoir_k,
                    distinct_k=self.config.obs_shadow_distinct_k,
                    link_rate=self.config.obs_shadow_link_rate,
                    pending_max=self.config.obs_shadow_pending_max,
                    max_services=core.config.max_services,
                    # deref the aggregator LAZILY: clear()/restore swap
                    # it wholesale, and the shadow must follow
                    sampler_ref=lambda: core.agg.sampler,
                    # get, never intern: a read-side plane must not
                    # perturb the id streams it audits
                    svc_resolver=core.vocab.services.get,
                    # windowed ground truth (ISSUE 15): bucket the
                    # shadow's sub-streams at the time tier's epoch
                    # granularity so the accuracy rollup can audit
                    # sealed segments bucket-for-bucket
                    bucket_minutes=(
                        core.config.time_bucket_minutes
                        if getattr(core, "timetier", None) is not None
                        else 0
                    ),
                )
                self._accuracy = AccuracyEstimator(
                    core,
                    self._obs_shadow,
                    rollup_s=self.config.obs_shadow_rollup_s,
                )
                core.shadow = self._obs_shadow
                core.accuracy = self._accuracy
                self.collector.shadow = self._obs_shadow
                if self._mp_ingester is not None:
                    self._mp_ingester.shadow = self._obs_shadow
                self._obs_windows.on_tick(
                    lambda _w: self._accuracy.maybe_rollup()
                )
            # critpath stitcher on the windows ticker, BEFORE the
            # watchdog for the same reason as the accuracy rollup: each
            # tick folds completed ledger slots (feeding the
            # wire_to_durable histogram + saturation gauges) before burn
            # evaluation reads them, so alerts lag at most one tick.
            if (
                self._mp_ingester is not None
                and getattr(self._mp_ingester, "critpath", None) is not None
            ):
                self._obs_windows.on_tick(self._mp_ingester.critpath.on_tick)
            # query stitcher on the same ticker, also BEFORE the
            # watchdog: each tick folds completed query traces (feeding
            # the query_wall histogram; query_lock_wait lands directly
            # from the lock) before burn evaluation reads them.
            if self._querytrace is not None and self.config.obs_query_enabled:
                self._obs_windows.on_tick(self._querytrace.on_tick)
            # mirror publisher on the same ticker, after the stitchers
            # and BEFORE the watchdog: each tick cuts a fresh epoch (one
            # aggregator-lock hold runs all packed reads) so queries
            # serve at most one tick stale under continuous ingest, and
            # burn evaluation reads this tick's mirror gauges. paced:
            # when a publish costs more than a tick (slow device reads),
            # the duty-cycle cap leaves at least equal lock time free
            # between epochs for fresh reads and ingest.
            # time-tier sealer on the same ticker, BEFORE the mirror
            # publisher (ISSUE 15): each tick freezes finished device
            # time buckets into host segments, so the epoch the
            # publisher cuts next already serves demand-registered
            # windowed ``ttq:`` keys from sealed segments (no aggregator
            # lock in those computes).
            if getattr(core, "timetier", None) is not None:
                self._obs_windows.on_tick(lambda _w: core.tt_seal())
            if self._mirror is not None and self._mirror.enabled:
                _mirror_core = getattr(
                    self.storage, "delegate", self.storage
                )
                self._obs_windows.on_tick(
                    lambda _w: _mirror_core.publish_mirror(paced=True)
                )
            if self.config.obs_slo_enabled:
                from zipkin_tpu.obs.slo import SloWatchdog, default_specs

                self._obs_slo = SloWatchdog(
                    self._obs_windows,
                    default_specs(
                        short_s=self.config.obs_slo_short_s,
                        long_s=self.config.obs_slo_long_s,
                        burn_threshold=self.config.obs_slo_burn_threshold,
                    ),
                )
                # incident capture (obs/incidents.py): every SLO trip
                # snapshots the volatile planes — slow ring, windowed
                # percentiles, waterfalls — into a bounded-retention
                # bundle before the evidence rotates out.
                if self.config.obs_incident_dir:
                    from zipkin_tpu.obs.incidents import IncidentRecorder

                    self._obs_incidents = IncidentRecorder(
                        self.config.obs_incident_dir,
                        retention=self.config.obs_incident_retention,
                    )
                    self._wire_incident_sources(core)
                    self._obs_slo.on_trip.append(
                        self._obs_incidents.on_slo_trip
                    )
        # overload control plane (runtime/overload.py, ISSUE 13): folds
        # the published signals into the brownout ladder every telemetry
        # tick. Constructed even without the windowed plane (tests and
        # embedders drive evaluate() directly); when windows run, the
        # controller subscribes AFTER the stitchers — it reads the
        # gauges the same tick just folded.
        self._overload = None
        if self.config.overload_enabled:
            from zipkin_tpu.runtime.overload import OverloadController

            core = getattr(self.storage, "delegate", self.storage)
            self._overload = OverloadController(
                enter=(
                    self.config.overload_enter_b1,
                    self.config.overload_enter_b2,
                    self.config.overload_enter_b3,
                ),
                exit_margin=self.config.overload_exit_margin,
                dwell_ticks=self.config.overload_dwell_ticks,
                max_stale_ms=self.config.overload_max_stale_ms,
                retry_base_s=self.config.overload_retry_base_s,
                # B2 bulk sheds nudge the sampling tier's pressure hook:
                # sustained overload degrades into lower sampling rates
                # instead of an ever-taller wall of 429s
                rate_controller=getattr(core, "sampling_controller", None),
            )
            # ingest admission gate: the collector consults the ladder
            # before any parse or queue hand-off
            self.collector.overload = self._overload
            # read-mode seam: the store's cached-read path serves
            # cache-first (B1/B2) / cache-only (B3) within the stated
            # staleness bound
            core.overload = self._overload
            # B1 observability shed: self-spans and slowest-chunk
            # timelines are the first cargo overboard
            if self._obs_emitter is not None:
                self._obs_emitter.gate = self._overload.shed_observability
            if self._obs_windows is not None:
                self._obs_windows.on_tick(self._overload.on_tick)
            # every ladder transition is an incident: capture the flight
            # around the brownout before the volatile planes rotate
            if self._obs_incidents is not None:
                self._obs_incidents.add_source(
                    "overload", self._overload.status
                )
                rec = self._obs_incidents
                self._overload.on_transition.append(
                    lambda ev: rec.capture({
                        "kind": "overload_transition",
                        "name": f"overload-{ev['from']}-to-{ev['to']}",
                        **ev,
                    })
                )
            # tenant-isolated admission (runtime/tenant.py, ISSUE 18):
            # per-tenant ingest budgets and tenant-scoped brownout
            # levels folded by the controller each tick. Constructed
            # even with a zero budget (accounting-only) so per-tenant
            # counters and /statusz rows always publish; enforcement
            # arms when TPU_TENANT_INGEST_BYTES_PER_S > 0.
            if self.config.tenant_enabled:
                from zipkin_tpu.runtime.tenant import TenantAdmission

                retained_table = None
                rc = getattr(core, "sampling_controller", None)
                if self.config.tenant_retained_spans_per_s > 0:
                    from zipkin_tpu.sampling.controller import (
                        TenantBudgetTable,
                    )

                    # retained-spans/sec budget, charged at dispatcher
                    # ack time (span counts are only known post-parse)
                    # and consulted by admit() before accepting more
                    # bytes from a tenant already in debt
                    retained_table = TenantBudgetTable(
                        spans_per_s=self.config.tenant_retained_spans_per_s,
                        burst_s=self.config.tenant_ingest_burst_s,
                        max_tenants=self.config.tenant_max,
                    )
                    if rc is not None:
                        rc.tenant_table = retained_table
                ta = TenantAdmission(
                    bytes_per_s=self.config.tenant_ingest_bytes_per_s,
                    burst_s=self.config.tenant_ingest_burst_s,
                    max_tenants=self.config.tenant_max,
                    flood_ratio=self.config.tenant_flood_ratio,
                    dwell_ticks=self.config.tenant_dwell_ticks,
                    retained_table=retained_table,
                )
                self._overload.tenant_admission = ta
                if self._mp_ingester is not None:
                    # the dispatcher attributes each acked payload's
                    # span count back to its tenant (thread-safe sink)
                    self._mp_ingester.tenant_sink = ta.note_retained
                # tenant-scoped SLOs (PR 9 grammar): one shed-ratio
                # spec per TPU_TENANT_SLO entry, evaluated over that
                # tenant's own counters only
                if self._obs_slo is not None and self.config.tenant_slo_tenants:
                    from zipkin_tpu.obs.slo import tenant_specs

                    for t in self.config.tenant_slo_tenants:
                        for spec in tenant_specs(
                            t,
                            short_s=self.config.obs_slo_short_s,
                            long_s=self.config.obs_slo_long_s,
                            burn_threshold=self.config.obs_slo_burn_threshold,
                        ):
                            self._obs_slo.add_spec(spec)
        self.components: Dict[str, Component] = {self.config.storage_type: self.storage}
        self._runner: Optional[web.AppRunner] = None
        self._grpc = None
        self._scribe = None
        self._snapshot_task = None

    # -- app ---------------------------------------------------------------

    def make_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        if self.config.deadline_propagation_enabled:
            # outermost: stamp the caller's X-Request-Timeout-Ms budget
            # before any other middleware spends time on the request
            app.middlewares.append(self._deadline_middleware)
        if self.config.self_tracing_enabled:
            from zipkin_tpu.server.self_tracing import self_tracing_middleware

            app.middlewares.append(
                self_tracing_middleware(
                    Collector(
                        self.storage,
                        metrics=self.metrics.for_transport("self"),
                    ),
                    sample_rate=self.config.self_tracing_sample_rate,
                )
            )
        r = app.router
        if self.config.http_collector_enabled:
            r.add_post("/api/v2/spans", self.post_spans_v2)
            r.add_post("/api/v1/spans", self.post_spans_v1)
        r.add_get("/api/v2/traces", self.get_traces)
        r.add_get("/api/v2/trace/{trace_id}", self.get_trace)
        r.add_get("/api/v2/traceMany", self.get_trace_many)
        r.add_get("/api/v2/services", self.get_services)
        r.add_get("/api/v2/spans", self.get_span_names)
        r.add_get("/api/v2/remoteServices", self.get_remote_services)
        r.add_get("/api/v2/dependencies", self.get_dependencies)
        r.add_get("/api/v2/autocompleteKeys", self.get_autocomplete_keys)
        r.add_get("/api/v2/autocompleteValues", self.get_autocomplete_values)
        if hasattr(self.storage, "latency_quantiles"):
            # TPU aggregation tier extensions (sketch-served reads)
            r.add_get("/api/v2/tpu/percentiles", self.get_tpu_percentiles)
            r.add_get("/api/v2/tpu/cardinalities", self.get_tpu_cardinalities)
            r.add_get("/api/v2/tpu/counters", self.get_tpu_counters)
            r.add_get("/api/v2/tpu/overview", self.get_tpu_overview)
            r.add_post("/api/v2/tpu/snapshot", self.post_tpu_snapshot)
        # flight-recorder debug plane: the recorder is process-global,
        # so this serves regardless of the storage tier
        r.add_get("/api/v2/tpu/statusz", self.get_tpu_statusz)
        r.add_get("/health", self.get_health)
        r.add_get("/info", self.get_info)
        r.add_get("/metrics", self.get_metrics)
        r.add_get("/prometheus", self.get_prometheus)
        r.add_get("/config.json", self.get_ui_config)
        r.add_get("/zipkin/", self.get_ui)
        r.add_get("/zipkin", self.get_ui)
        r.add_get("/zipkin/static/{name}", self.get_ui_asset)
        return app

    # Span fields are attacker-controlled and the app renders them; even
    # with the esc() discipline (pinned by tests/test_ui_assets.py) the
    # UI ships defense-in-depth: only same-origin scripts execute, so an
    # escaping regression cannot become script execution. 'unsafe-inline'
    # styles stay allowed — the app positions bars with style attributes.
    _UI_CSP = (
        "default-src 'self'; script-src 'self'; style-src 'self' "
        "'unsafe-inline'; img-src 'self' data:; object-src 'none'; "
        "base-uri 'none'; frame-ancestors 'none'"
    )

    async def get_ui(self, request: web.Request) -> web.Response:
        from zipkin_tpu.server.ui import index_page

        return web.Response(
            text=index_page(), content_type="text/html",
            headers={"Content-Security-Policy": self._UI_CSP},
        )

    async def get_ui_asset(self, request: web.Request) -> web.Response:
        from zipkin_tpu.server.ui import asset

        found = asset(request.match_info["name"])
        if found is None:
            return web.Response(status=404, text="no such asset")
        body, ctype = found
        return web.Response(
            body=body, content_type=ctype,
            headers={"Content-Security-Policy": self._UI_CSP},
        )

    async def start(self) -> "ZipkinServer":
        app = self.make_app()
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.config.host, self.config.port)
        await site.start()
        if self.config.grpc_collector_enabled:
            from zipkin_tpu.server.grpc import GrpcCollectorServer

            grpc_collector = Collector(
                self.storage,
                sampler=self.collector.sampler,
                metrics=self.metrics.for_transport("grpc"),
                # without this the gRPC tier decodes proto3 on the
                # Python object path (~15k spans/s measured) while
                # HTTP rides the native parser — the r4 "line-rate
                # gRPC" claim depends on the fast path here too
                fast_ingest=self.config.tpu_fast_ingest,
                # SpanService/Report routes into the SAME parse
                # fan-out as HTTP (ISSUE 8): proto3 is the tier's
                # preferred wire, not the odd one out
                mp_ingester=self._mp_ingester,
                shadow=self._obs_shadow,
            )
            # same brownout admission as HTTP: the ladder must not have
            # a transport-shaped hole in it
            grpc_collector.overload = self._overload
            self._grpc = GrpcCollectorServer(
                grpc_collector,
                host=self.config.host,
                port=self.config.grpc_port,
                deadlines=self.config.deadline_propagation_enabled,
            )
            await self._grpc.start()
        if self.config.scribe_enabled:
            from zipkin_tpu.collector.scribe import ScribeCollector

            self._scribe = ScribeCollector(
                Collector(
                    self.storage,
                    sampler=self.collector.sampler,
                    metrics=self.metrics.for_transport("scribe"),
                    shadow=self._obs_shadow,
                ),
                host=self.config.host,
                port=self.config.scribe_port,
            )
            await self._scribe.start()
            self.components["scribe"] = self._scribe
        if (
            self.config.tpu_snapshot_interval_s > 0
            and getattr(self.storage, "checkpoint_dir", None)
            and hasattr(self.storage, "snapshot")
        ):
            # periodic snapshots close the durability loop: they bound
            # WAL growth (segments covered by a snapshot are deleted)
            # and bound the replay window after a crash. The reference
            # has no in-process analog — its durability is the storage
            # backend's (SURVEY.md §5 checkpoint row).
            self._snapshot_task = asyncio.create_task(
                self._snapshot_loop(self.config.tpu_snapshot_interval_s)
            )
        if self._obs_windows is not None:
            self._obs_windows.start_ticker()
        logger.info("zipkin-tpu listening on :%d", self.config.port)
        return self

    async def _snapshot_loop(self, interval_s: float) -> None:
        while True:
            await asyncio.sleep(interval_s)
            try:
                path = await asyncio.to_thread(self.storage.snapshot)
                logger.info("periodic snapshot -> %s", path)
            except asyncio.CancelledError:  # pragma: no cover
                raise
            except Exception:  # pragma: no cover - keep the loop alive
                logger.exception("periodic snapshot failed; will retry")

    async def stop(self) -> None:
        if self._obs_windows is not None:
            # first: the ticker's counter source reads the storage,
            # which teardown below closes
            await asyncio.to_thread(self._obs_windows.stop_ticker)
        take_final_snapshot = self._snapshot_task is not None
        if self._snapshot_task is not None:
            self._snapshot_task.cancel()
            try:
                await self._snapshot_task
            except (asyncio.CancelledError, Exception):
                pass
            self._snapshot_task = None
        if self._scribe is not None:
            await self._scribe.stop()
            self._scribe = None
        if self._grpc is not None:
            await self._grpc.stop()
            self._grpc = None
        if self._runner is not None:
            await self._runner.cleanup()
        if self._mp_ingester is not None:
            try:
                # finish queued payloads before teardown (202s issued)
                await asyncio.to_thread(self._mp_ingester.drain)
            except Exception:
                logger.exception("mp-ingest drain failed during stop")
            finally:
                # close() must always run: it joins the worker processes
                # and unlinks the shared-memory block
                await asyncio.to_thread(self._mp_ingester.close)
                self._mp_ingester = None
        if self._obs_emitter is not None:
            # before any final snapshot: the emitter's last flush feeds
            # spans into storage, and stop() disarms the global recorder
            # budgets/hook this server installed
            try:
                await asyncio.to_thread(self._obs_emitter.stop)
            finally:
                self._obs_emitter = None
        if take_final_snapshot:
            # final snapshot LAST: collectors are stopped and the MP
            # queue drained, so every 202-acked span is in storage —
            # snapshotting earlier would strand post-snapshot spans in
            # the WAL (or, without a WAL, lose them)
            try:
                await asyncio.to_thread(self.storage.snapshot)
            except Exception:  # pragma: no cover
                logger.exception("shutdown snapshot failed")
        self.storage.close()

    # -- deadlines + backoff guidance (ISSUE 13) ---------------------------

    @web.middleware
    async def _deadline_middleware(self, request, handler):
        """Stamp the caller's ``X-Request-Timeout-Ms`` budget at the
        earliest server-side instant; handlers check it right before
        their expensive dispatch. gRPC carries the same contract via
        its native deadline (``context.time_remaining``)."""
        raw = request.headers.get("X-Request-Timeout-Ms")
        if raw:
            try:
                budget_ms = float(raw)
            except ValueError:
                budget_ms = None  # malformed header: no deadline
            if budget_ms is not None:
                request["deadline_mono"] = (
                    time.monotonic() + max(0.0, budget_ms) / 1000.0
                )
        return await handler(request)

    def _deadline_expired(self, request) -> Optional[web.Response]:
        """504 when the caller's budget is already spent — counted on
        the controller so ``deadlineExpired`` surfaces on /metrics."""
        deadline = request.get("deadline_mono")
        if deadline is None or time.monotonic() <= deadline:
            return None
        if self._overload is not None:
            self._overload.note_deadline_expired()
        return web.Response(
            status=504,
            text="deadline expired before dispatch",
            headers={"X-Deadline-Expired": "1"},
        )

    def _backoff_headers(self, exc=None) -> Dict[str, str]:
        """Retry guidance for a shed: ``Retry-After`` is RFC
        delta-seconds (integer, so ceil); ``X-Retry-After-Ms`` preserves
        sub-second precision. When the shed carries a scope (ISSUE 18)
        the delay is the one the rejecting control computed — a
        tenant-budget shed advertises THAT tenant's bucket deficit, not
        the global ladder's jittered backoff — and
        ``X-Shed-Scope``/``X-Shed-Tenant`` say which control rejected
        the payload."""
        if self._overload is None:
            return {}
        delay_s = getattr(exc, "retry_after_s", None)
        scope = getattr(exc, "scope", None)
        tenant = getattr(exc, "tenant", None)
        if delay_s is None:
            delay_s = self._overload.retry_after_s(
                tenant if scope == "tenant" else None
            )
        headers = {
            "Retry-After": str(max(1, int(-(-delay_s // 1)))),
            "X-Retry-After-Ms": str(int(delay_s * 1000.0)),
        }
        if scope:
            headers["X-Shed-Scope"] = str(scope)
        if tenant:
            headers["X-Shed-Tenant"] = str(tenant)
        return headers

    # -- ingest ------------------------------------------------------------

    MAX_INFLATED = 256 * 1024 * 1024  # decompression-bomb guard

    async def _read_body(self, request: web.Request) -> bytes:
        # aiohttp transparently inflates Content-Encoding: gzip; the magic
        # check also covers clients that compress without the header. Inflate
        # incrementally with a size cap: client_max_size only bounds the
        # COMPRESSED bytes, so a gzip bomb must not materialize unbounded.
        body = await request.read()
        if body[:2] == b"\x1f\x8b":
            import zlib

            chunks: List[bytes] = []
            total = 0
            remaining = body
            while remaining:  # multi-member gzip is valid per RFC 1952
                d = zlib.decompressobj(wbits=31)
                out = d.decompress(remaining, self.MAX_INFLATED - total)
                total += len(out)
                if d.unconsumed_tail:
                    raise PayloadTooLarge(
                        f"gzip payload inflates past {self.MAX_INFLATED} bytes"
                    )
                chunks.append(out)
                remaining = d.unused_data
            body = b"".join(chunks)
        return body

    async def post_spans_v2(self, request: web.Request) -> web.Response:
        return await self._ingest(request, v1=False)

    async def post_spans_v1(self, request: web.Request) -> web.Response:
        return await self._ingest(request, v1=True)

    # zt-ingest-boundary: HTTP POST /api/v{1,2}/spans is a wire
    # entrypoint — tenant identity is extracted from X-Tenant-Id here,
    # before the collector chokepoint runs admission
    async def _ingest(self, request: web.Request, *, v1: bool) -> web.Response:
        t0 = time.perf_counter()
        # critpath wire anchor: the same instant http_boundary measures
        # from, in the ns domain the interval ledger uses. Contextvars
        # survive asyncio.to_thread, so the MP submit path reads it.
        critpath.WIRE_T0_NS.set(int(t0 * 1e9))
        # tenant admission identity (ISSUE 18): absent or hostile header
        # values normalize to the default tenant, so legacy clients keep
        # flowing; the collector chokepoint reads the contextvar (which
        # survives asyncio.to_thread) for budget attribution
        CURRENT_TENANT.set(
            normalize_tenant(request.headers.get(TENANT_HEADER))
        )
        try:
            body = await self._read_body(request)
        except PayloadTooLarge as e:
            return web.Response(status=413, text=str(e))
        except Exception:
            return web.Response(status=400, text="cannot gunzip body")
        ctype = request.headers.get("Content-Type", "").split(";")[0].strip()
        encoding: Optional[Encoding] = None
        if ctype == "application/x-protobuf":
            encoding = Encoding.PROTO3
        elif ctype == "application/x-thrift":
            encoding = Encoding.THRIFT
        elif ctype == JSON and v1:
            encoding = Encoding.JSON_V1
        # else: sniff (covers missing/odd content types)
        # deadline propagation (ISSUE 13): the caller's budget may have
        # expired while the body was read — work already past its
        # deadline must be dropped BEFORE the collector dispatches it,
        # or an overloaded tier burns capacity on answers nobody awaits
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        try:
            await asyncio.to_thread(self.collector.accept_spans_bytes, body, encoding)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        except RejectedExecutionError as e:
            # storage throttle shed the write: tell the sender to back off
            # (reference behavior for RejectedExecutionException)
            return web.Response(status=503, text=str(e))
        except IngestBackpressure as e:
            # a tenant budget shed the payload, every parse-worker
            # queue in the fan-out tier is full, or the global brownout
            # ladder shed it: 429 (Too Many Requests) — transient,
            # retryable, distinct from the throttle's 503 so dashboards
            # can tell the tiers apart. Retry-After carries backoff
            # scoped to whichever control rejected the payload
            # (X-Shed-Scope: tenant|global, ISSUE 18); the millisecond
            # twin keeps sub-second precision visible to clients that
            # want to decorrelate.
            return web.Response(
                status=429, text=str(e), headers=self._backoff_headers(e)
            )
        # body read → collector hand-off complete; the 202 ack follows
        obs.record("http_boundary", time.perf_counter() - t0)
        return web.Response(status=202)

    # -- query -------------------------------------------------------------

    def _parse_query(self, request: web.Request) -> QueryRequest:
        q = request.query

        def opt_int(name: str) -> Optional[int]:
            raw = q.get(name)
            return int(raw) if raw else None

        import time

        end_ts = opt_int("endTs") or int(time.time() * 1000)
        lookback = opt_int("lookback") or self.config.default_lookback
        return QueryRequest(
            end_ts=end_ts,
            lookback=lookback,
            limit=opt_int("limit") or self.config.query_limit,
            service_name=q.get("serviceName"),
            remote_service_name=q.get("remoteServiceName"),
            span_name=q.get("spanName"),
            annotation_query=parse_annotation_query(q.get("annotationQuery")),
            min_duration=opt_int("minDuration"),
            max_duration=opt_int("maxDuration"),
        )

    async def get_traces(self, request: web.Request) -> web.Response:
        try:
            query = self._parse_query(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        traces = await asyncio.to_thread(
            lambda: self.storage.span_store().get_traces_query(query).execute()
        )
        return web.json_response(
            [[json_v2.span_to_dict(s) for s in t] for t in traces]
        )

    async def get_trace(self, request: web.Request) -> web.Response:
        raw_id = request.match_info["trace_id"]
        try:
            normalize_trace_id(raw_id)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        spans = await asyncio.to_thread(
            lambda: self.storage.span_store().get_trace(raw_id).execute()
        )
        if not spans:
            return web.Response(status=404, text=f"trace {raw_id} not found")
        return web.json_response([json_v2.span_to_dict(s) for s in spans])

    async def get_trace_many(self, request: web.Request) -> web.Response:
        raw = request.query.get("traceIds", "")
        ids = [x for x in raw.split(",") if x]
        if not ids:
            return web.Response(status=400, text="traceIds parameter is required")
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        traces = await asyncio.to_thread(
            lambda: self.storage.traces().get_traces(ids).execute()
        )
        return web.json_response(
            [[json_v2.span_to_dict(s) for s in t] for t in traces]
        )

    async def get_services(self, request: web.Request) -> web.Response:
        names = await asyncio.to_thread(
            lambda: self.storage.service_and_span_names().get_service_names().execute()
        )
        return web.json_response(names)

    async def get_span_names(self, request: web.Request) -> web.Response:
        service = request.query.get("serviceName", "")
        names = await asyncio.to_thread(
            lambda: self.storage.service_and_span_names()
            .get_span_names(service)
            .execute()
        )
        return web.json_response(names)

    async def get_remote_services(self, request: web.Request) -> web.Response:
        service = request.query.get("serviceName", "")
        names = await asyncio.to_thread(
            lambda: self.storage.service_and_span_names()
            .get_remote_service_names(service)
            .execute()
        )
        return web.json_response(names)

    @staticmethod
    def _staleness_param(request: web.Request) -> Optional[float]:
        """Per-request mirror staleness bound (ms). ``staleness_ms<=0``
        forces the fresh locked read; absent means the server default.
        Raises ValueError on garbage (callers 400 it)."""
        raw = request.query.get("staleness_ms")
        return float(raw) if raw is not None else None

    async def get_dependencies(self, request: web.Request) -> web.Response:
        raw_end = request.query.get("endTs")
        if not raw_end:
            return web.Response(status=400, text="endTs parameter is required")
        try:
            end_ts = int(raw_end)
            lookback = int(request.query.get("lookback") or self.config.default_lookback)
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        # per-request staleness bound routes through only when the
        # backing store HAS a mirror (the in-memory tier's SPI signature
        # stays byte-compatible with the reference)
        kwargs = (
            {"staleness_ms": staleness}
            if staleness is not None and self._mirror is not None
            else {}
        )
        links = await asyncio.to_thread(
            lambda: self.storage.span_store()
            .get_dependencies(end_ts, lookback, **kwargs)
            .execute()
        )
        return web.json_response([json_v2.link_to_dict(x) for x in links])

    async def get_autocomplete_keys(self, request: web.Request) -> web.Response:
        keys = await asyncio.to_thread(
            lambda: self.storage.autocomplete_tags().get_keys().execute()
        )
        return web.json_response(keys)

    async def get_autocomplete_values(self, request: web.Request) -> web.Response:
        key = request.query.get("key")
        if not key:
            return web.Response(status=400, text="key parameter is required")
        values = await asyncio.to_thread(
            lambda: self.storage.autocomplete_tags().get_values(key).execute()
        )
        return web.json_response(values)

    # -- TPU aggregation tier extensions -----------------------------------
    # Not part of the reference HTTP surface: these serve the sketch reads
    # the BASELINE north star adds (latency percentiles, trace cardinality)
    # straight from device state. The Lens-compatible endpoints above stay
    # byte-compatible; these are additive under /api/v2/tpu/.

    async def get_tpu_percentiles(self, request: web.Request) -> web.Response:
        raw_q = request.query.get("q", "0.5,0.9,0.99")
        try:
            qs = [float(x) for x in raw_q.split(",") if x]
            if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
                raise ValueError(f"q out of range: {raw_q!r}")
            # optional endTs/lookback (ms, the query-API convention) route
            # to the time-sliced histograms — windowed percentiles
            end_ts = request.query.get("endTs")
            lookback = request.query.get("lookback")
            end_ts = int(end_ts) if end_ts is not None else None
            lookback = int(lookback) if lookback is not None else None
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        rows = await asyncio.to_thread(
            self.storage.latency_quantiles,
            qs,
            request.query.get("serviceName"),
            request.query.get("spanName"),
            request.query.get("sketch", "digest") == "digest",
            end_ts,
            lookback,
            staleness,
        )
        return web.json_response(rows)

    async def get_tpu_cardinalities(self, request: web.Request) -> web.Response:
        try:
            staleness = self._staleness_param(request)
            # optional endTs/lookback (ms, the query-API convention)
            # route to the time tier — windowed cardinalities over the
            # covering bucket segments (HLL register-max merge)
            end_ts = request.query.get("endTs")
            lookback = request.query.get("lookback")
            end_ts = int(end_ts) if end_ts is not None else None
            lookback = int(lookback) if lookback is not None else None
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        return web.json_response(
            await asyncio.to_thread(
                self.storage.trace_cardinalities, staleness, end_ts, lookback
            )
        )

    async def get_tpu_counters(self, request: web.Request) -> web.Response:
        return web.json_response(
            await asyncio.to_thread(self.storage.ingest_counters)
        )

    async def get_tpu_overview(self, request: web.Request) -> web.Response:
        """Percentiles + cardinalities + counters in ONE storage read —
        one aggregator dispatch and one device→host transfer — instead
        of the three requests the UI sketch page used to issue."""
        if not hasattr(self.storage, "sketch_overview"):
            return web.Response(
                status=501, text="storage does not serve sketch_overview"
            )
        raw_q = request.query.get("q", "0.5,0.9,0.99")
        try:
            qs = [float(x) for x in raw_q.split(",") if x]
            if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
                raise ValueError(f"q out of range: {raw_q!r}")
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        expired = self._deadline_expired(request)
        if expired is not None:
            return expired
        body = await asyncio.to_thread(
            self.storage.sketch_overview,
            qs,
            request.query.get("serviceName"),
            request.query.get("spanName"),
            staleness,
        )
        return web.json_response(body)

    async def post_tpu_snapshot(self, request: web.Request) -> web.Response:
        if not hasattr(self.storage, "snapshot"):
            return web.Response(status=501, text="storage does not snapshot")
        path = await asyncio.to_thread(self.storage.snapshot)
        if path is None:
            return web.Response(status=409, text="no checkpoint_dir configured")
        return web.json_response({"snapshot": path})

    # -- ops ---------------------------------------------------------------

    async def get_health(self, request: web.Request) -> web.Response:
        results = {}
        overall_up = True
        for name, component in self.components.items():
            result = await asyncio.to_thread(component.check)
            results[name] = {
                "status": "UP" if result.ok else "DOWN",
                **({"error": str(result.error)} if result.error else {}),
            }
            overall_up &= result.ok
        body = {"status": "UP" if overall_up else "DOWN", "zipkin": results}
        return web.json_response(body, status=200 if overall_up else 503)

    async def get_info(self, request: web.Request) -> web.Response:
        return web.json_response(
            {"zipkin": {"version": zipkin_tpu.__version__, "flavor": "tpu"}}
        )

    def _window_counter_source(self) -> dict:
        """Counters the windowed plane samples each tick: transport-
        summed collector tallies (the wire-to-ack SLO's numerators) plus
        the storage tier's flat ingest counters."""
        sums = {"messages": 0, "messages_dropped": 0,
                "spans": 0, "spans_dropped": 0}
        for key, value in self.metrics.snapshot().items():
            _, _, name = key.partition(".")
            if name in sums:
                sums[name] += value
        out = {
            "collectorMessages": sums["messages"],
            "collectorMessagesDropped": sums["messages_dropped"],
            "collectorSpans": sums["spans"],
            "collectorSpansDropped": sums["spans_dropped"],
        }
        if hasattr(self.storage, "ingest_counters"):
            try:
                out.update(self.storage.ingest_counters())
            except Exception:
                pass
        # per-tenant admission counters (ISSUE 18): the windowed plane
        # must see tenantOffered_<slug>/tenantShed_<slug> so the
        # tenant-scoped shed-ratio SloSpecs can burn against them
        if self._overload is not None:
            try:
                out.update(self._overload.counters())
            except Exception:
                pass
        return out

    def _windows_catch_up(self) -> None:
        """Read-path tick driver: keeps windows/SLO fresh on servers
        that never ran start() (TestServer embedding). Blocking —
        call via asyncio.to_thread."""
        w = self._obs_windows
        if w is not None and not w.ticker_running:
            w.tick_if_due()

    def _wire_incident_sources(self, core) -> None:
        """Register the statusz-equivalent dict builders an incident
        bundle snapshots. The recorder wraps each source in its own
        try/except, so a torn plane degrades to an error note inside
        the bundle instead of losing it."""
        rec = self._obs_incidents
        rec.add_source("slo", self._obs_slo.status)
        rec.add_source("windows", self._obs_windows.status)
        rec.add_source("stages", lambda: {
            st.stage: {"count": st.count, "p50Us": st.p50_us,
                       "p99Us": st.p99_us, "maxUs": st.max_us}
            for st in obs.RECORDER.snapshot().nonzero()
        })
        rec.add_source("slowRing", obs.RECORDER.slow_events)
        if hasattr(core, "ingest_counters"):
            rec.add_source("counters", core.ingest_counters)
        if self._querytrace is not None:
            rec.add_source("queries", self._querytrace.waterfall)
        ing = self._mp_ingester
        cp = getattr(ing, "critpath", None) if ing is not None else None
        if cp is not None:
            rec.add_source("critpath", cp.waterfall)

    async def get_metrics(self, request: web.Request) -> web.Response:
        """Actuator-style counters, reference taxonomy kept verbatim:
        ``counter.zipkin_collector.spans.http`` etc."""
        out = {}
        for key, value in self.metrics.snapshot().items():
            transport, _, name = key.partition(".")
            out[f"counter.zipkin_collector.{name}.{transport}"] = value
        # boot-time restore gauges (ISSUE 3): cost of the last recovery
        restore = getattr(self.storage, "restore_stats", None)
        if restore:
            for name, value in restore.items():
                out[f"gauge.zipkin_tpu.{name}"] = value
        # incremental link-ctx gauges (ISSUE 5): since-rollup delta size,
        # advance count, and host wall of the last ctx-advancing dispatch
        counters = None
        if hasattr(self.storage, "ingest_counters"):
            counters = await asyncio.to_thread(self.storage.ingest_counters)
            for name in ("ctxDeltaLanes", "ctxAdvances", "ctxMaintenanceMs"):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            # fan-out tier gauges (ISSUE 8): pool size/health, bounded-queue
            # posture, and the acked-span accounting that proves zero loss
            for name in (
                "mpWorkers", "mpWorkersAlive", "mpQueueDepth", "mpInflight",
                "mpAccepted", "mpSampleDropped", "mpFallbacks", "mpRejected",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            # critical-path stitcher (ISSUE 11): timeline accounting and
            # the Little's-law saturation gauges behind the queue SLO
            for name in (
                "critpathTimelines", "critpathSkipped", "critpathAbandoned",
                "critpathReclaimed", "critpathDegraded", "critpathTruncated",
                "critpathLambdaCps", "critpathLittleL",
                "critpathWorkerOccupancy", "critpathQueueSaturation",
                "critpathConservationP50Milli",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            # query-plane observatory (ISSUE 12): stitched query walls,
            # the aggregator-lock contention ledger, and cached-read
            # staleness (age-at-serve)
            for name in (
                "queryTraces", "queryWallP50Us", "queryWallP99Us",
                "queryWallMaxUs", "queryConservationP50Milli",
                "queryLockAcquisitions", "queryLockContended",
                "queryLockReentries", "queryLockWaiters",
                "queryLockWaitersHighWater", "queryLockWaitP50Us",
                "queryLockWaitP99Us", "queryLockWaitMaxUs",
                "queryLockHoldP50Us", "queryLockHoldP99Us",
                "queryLockHoldMaxUs", "readCacheServeAgeMs",
                "readCacheServeAgeMaxMs", "readCacheEntries",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            # epoch-published read mirror (ISSUE 14): publish cadence,
            # serve tallies, and staleness-at-serve — the gauges the
            # query_mirror_staleness SLO and the r08 bench read
            for name in (
                "mirrorGeneration", "mirrorPublishes", "mirrorPublishSkips",
                "mirrorPublishBackoffs",
                "mirrorPublishMs", "mirrorServes", "mirrorStaleServes",
                "mirrorMisses", "mirrorServeAgeMs", "mirrorServeAgeMaxMs",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            # scale-out read serving (ISSUE 19): shm segment publication
            # ledger + the reader-fleet heartbeat rollup (demand-ring
            # traffic, max staleness over alive readers, respawns)
            for name in (
                "segmentGeneration", "segmentPublishes",
                "segmentPublishErrors", "segmentOverflows",
                "segmentSkippedKeys", "segmentPayloadBytes",
                "segmentSerializeMs", "mirrorSegmentSinkErrors",
                "readerRespawns", "readerDemandRequests",
                "readerDemandOverflow", "readerDemandUnparsed",
                "readerServeAgeMs", "readerGenerationLagMax",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
        # sampling-tier gauges (ISSUE 4): retention verdict tallies, the
        # controller's budget posture, and the live per-service keep rate
        if getattr(self.storage, "sampler", None) is not None:
            if counters is None:
                counters = await asyncio.to_thread(
                    self.storage.ingest_counters
                )
            for name in (
                "sampledKept", "sampledDropped", "budgetUtilization",
                "samplerPublishes", "samplerPressure",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
            rates = await asyncio.to_thread(self.storage.sampler_rates)
            for svc, rate in sorted(rates.items()):
                out[f"gauge.zipkin_tpu.samplerRate.{svc}"] = rate
        # durability-plane gauges (ISSUE 7): at-rest scrub progress and
        # quarantine tallies (restoreFallbacks / generationsQuarantined
        # already flow via the restore_stats block above)
        if counters:
            for name in (
                "scrubBytes", "scrubPasses", "scrubCorruptDetected",
                "segmentsQuarantined", "spansQuarantined",
                "archiveSegmentsQuarantined", "archiveSpansQuarantined",
            ):
                if name in counters:
                    out[f"gauge.zipkin_tpu.{name}"] = counters[name]
        # accuracy observatory (ISSUE 10): relative-error gauges from the
        # latest rollup plus the shadow's own occupancy counters
        if self._accuracy is not None:
            acc = await asyncio.to_thread(self._accuracy.export_counters)
            for name, value in sorted(acc.items()):
                out[f"gauge.zipkin_tpu.{name}"] = value
        # pipeline flight recorder (zipkin_tpu.obs): per-stage quantiles
        for st in obs.RECORDER.snapshot().nonzero():
            out[f"gauge.zipkin_tpu.stage.{st.stage}.p50Us"] = st.p50_us
            out[f"gauge.zipkin_tpu.stage.{st.stage}.p99Us"] = st.p99_us
            out[f"gauge.zipkin_tpu.stage.{st.stage}.maxUs"] = st.max_us
        # SLO watchdog verdicts (ISSUE 9): alert flag + per-window burn
        if self._obs_slo is not None:
            await asyncio.to_thread(self._windows_catch_up)
            for v in await asyncio.to_thread(self._obs_slo.verdicts):
                base = f"gauge.zipkin_tpu.slo.{v['name']}"
                out[f"{base}.alert"] = int(v["alert"])
                for wname, wv in v["windows"].items():
                    out[f"{base}.burn.{wname}"] = wv["burn"]
        # overload control plane (ISSUE 13): ladder level, load index,
        # per-class admit/shed tallies, deadline drops
        if self._overload is not None:
            for name, value in self._overload.counters().items():
                out[f"gauge.zipkin_tpu.{name}"] = value
        return web.json_response(out)

    async def get_prometheus(self, request: web.Request) -> web.Response:
        lines: List[str] = []
        # collector counters, one family per counter name, transport label
        by_name: Dict[str, List[Tuple[str, float]]] = {}
        for key, value in sorted(self.metrics.snapshot().items()):
            transport, _, name = key.partition(".")
            by_name.setdefault(name, []).append((transport, value))
        for name, rows in sorted(by_name.items()):
            fam = _prom_name(f"zipkin_collector_{name}_total")
            lines.append(
                f"# HELP {fam} Collector {name.replace('_', ' ')} by transport."
            )
            lines.append(f"# TYPE {fam} counter")
            for transport, value in rows:
                lines.append(
                    f'{fam}{{transport="{_prom_label(transport)}"}} {value}'
                )
        if hasattr(self.storage, "ingest_counters"):
            # device-tier gauges (sketch occupancy / ingest truth counters;
            # with the sampling tier armed this includes sampled_kept /
            # sampled_dropped / budget_utilization)
            counters = await asyncio.to_thread(self.storage.ingest_counters)
            for name, value in sorted(counters.items()):
                if isinstance(value, bool) or not isinstance(
                    value, (int, float)
                ):
                    continue  # nested tables (mpWorkerTable) ride /statusz
                fam = _prom_name(f"zipkin_tpu_{_snake(name)}")
                lines.append(f"# HELP {fam} Device-tier gauge {name}.")
                lines.append(f"# TYPE {fam} gauge")
                lines.append(f"{fam} {value}")
            lines.extend(_prom_mp_workers(counters.get("mpWorkerTable")))
            lines.extend(_prom_critpath(counters.get("critpathSegments")))
            # the flat queryLock*/queryWall* gauges rode the loop above;
            # this renders the labelled families (wait/hold histograms,
            # per-label holder attribution, per-segment aggregates)
            lines.extend(_prom_query_lock(counters.get("queryLock")))
            lines.extend(
                _prom_query_segments(counters.get("querySegments"))
            )
        if getattr(self.storage, "sampler", None) is not None:
            # live per-service keep probability (1.0 = keep everything)
            rates = await asyncio.to_thread(self.storage.sampler_rates)
            if rates:
                lines.append(
                    "# HELP zipkin_tpu_sampler_rate Live per-service keep "
                    "probability (1.0 = keep everything)."
                )
                lines.append("# TYPE zipkin_tpu_sampler_rate gauge")
                for svc, rate in sorted(rates.items()):
                    lines.append(
                        f'zipkin_tpu_sampler_rate{{service="{_prom_label(svc)}"}} {rate}'
                    )
        lines.extend(
            _prom_stage_histograms(
                obs.RECORDER.snapshot(), obs.RECORDER.slow_events()
            )
        )
        # accuracy observatory (ISSUE 10): the flat zipkin_tpu_accuracy_*
        # gauges already rode ingest_counters above; this adds the
        # per-service digest-error family (labels need their own render)
        if self._accuracy is not None:
            lines.extend(
                _prom_accuracy(await asyncio.to_thread(self._accuracy.status))
            )
        # SLO watchdog verdicts (ISSUE 9): boolean alert gauge (what pages)
        # plus the per-window burn rates behind it (what to graph)
        if self._obs_slo is not None:
            await asyncio.to_thread(self._windows_catch_up)
            lines.extend(
                _prom_slo(await asyncio.to_thread(self._obs_slo.verdicts))
            )
        # overload control plane (ISSUE 13): zipkin_tpu_overload_*
        # families — ladder posture, the folded signal set, admission
        # accounting, and deadline drops
        if self._overload is not None:
            status = self._overload.status()
            lines.extend(_prom_overload(status))
            # tenant isolation (ISSUE 18): {tenant=}-labelled admission
            # families, bounded by the tenant table's LRU cap
            lines.extend(_prom_tenants(status))
        return web.Response(text="\n".join(lines) + "\n")

    async def get_tpu_statusz(self, request: web.Request) -> web.Response:
        """Flight-recorder debug plane: full stage table, the recent
        over-budget event ring, and the recorder's own measured cost."""
        rec = obs.RECORDER
        snap = rec.snapshot()
        stages = {}
        for st in snap.stages():
            budget = rec.budget_us(st.stage)
            stages[st.stage] = {
                "count": st.count,
                "p50Us": st.p50_us,
                "p99Us": st.p99_us,
                "maxUs": st.max_us,
                "sumUs": st.sum_us,
                "budgetUs": int(budget) if budget != float("inf") else -1,
            }
        body = {
            "stages": stages,
            "slow": rec.slow_events(),
            "recorder": {
                "enabled": rec.enabled,
                "budgetScale": rec.budget_scale,
                "writerThreads": snap.locals_seen,
                "generation": snap.generation,
                "overheadNsPerRecord": await asyncio.to_thread(
                    rec.measure_overhead
                ),
                "selfSpans": self._obs_emitter is not None,
                "selfSpansEmitted": (
                    self._obs_emitter.emitted if self._obs_emitter else 0
                ),
            },
        }
        if (
            getattr(self.storage, "sampler", None) is not None
            and hasattr(self.storage, "ingest_counters")
        ):
            counters = await asyncio.to_thread(self.storage.ingest_counters)
            body["sampler"] = {
                name: counters[name]
                for name in (
                    "budgetUtilization", "samplerPublishes",
                    "samplerPressure", "sampledKept", "sampledDropped",
                )
                if name in counters
            }
        durability = await asyncio.to_thread(self._durability_status)
        if durability:
            body["durability"] = durability
        # windowed telemetry plane + SLO verdicts (ISSUE 9)
        if self._obs_windows is not None:
            await asyncio.to_thread(self._windows_catch_up)
            body["windows"] = await asyncio.to_thread(self._obs_windows.status)
        if self._obs_slo is not None:
            body["slo"] = await asyncio.to_thread(self._obs_slo.status)
        # accuracy observatory (ISSUE 10): the latest rollup's relative-
        # error gauges, per-service digest detail, and shadow occupancy
        if self._accuracy is not None:
            body["accuracy"] = await asyncio.to_thread(self._accuracy.status)
        # device-program observatory: compile counts, per-program device
        # wall, first-compile cost/memory analysis, HBM + transfer gauges
        from zipkin_tpu.obs.device import OBSERVATORY

        body["device"] = await asyncio.to_thread(OBSERVATORY.status)
        # per-worker attribution table from the fan-out tier (ISSUE 9
        # satellite): dispatcher-side tallies keyed by widx
        ing = getattr(self.storage, "mp_ingester", None)
        if ing is not None:
            stats = await asyncio.to_thread(ing.stats)
            if "mpWorkerTable" in stats:
                body["workers"] = stats["mpWorkerTable"]
            # ingest waterfall (ISSUE 11): exact windowed wire-to-durable,
            # queue-wait vs service decomposition, Little's-law gauges,
            # and the slowest folded chunk's segment timeline
            cp = getattr(ing, "critpath", None)
            if cp is not None:
                body["critpath"] = await asyncio.to_thread(cp.waterfall)
        # query-plane observatory (ISSUE 12): stitched per-query
        # waterfall (segment decomposition, conservation, the slowest
        # query) + the aggregator-lock contention ledger
        if self._querytrace is not None:
            body["queries"] = await asyncio.to_thread(
                self._querytrace.waterfall
            )
        # epoch-published read mirror (ISSUE 14): current snapshot epoch
        # (generation, write version, age) + publish/serve ledger
        if self._mirror is not None:
            body["mirror"] = await asyncio.to_thread(self._mirror.status)
        # scale-out read serving (ISSUE 19): shm segment generation,
        # payload size, and the per-reader heartbeat table (generation
        # lag, serve ages, demand-ring depth, respawn count) — the
        # segment name is here so `python -m zipkin_tpu.serving` can be
        # pointed at it (TPU_MIRROR_SEGMENT=<name>)
        seg = getattr(self.storage, "mirror_segment", None)
        if seg is not None:
            body["serving"] = await asyncio.to_thread(seg.status)
        # overload control plane (ISSUE 13): ladder state, the live
        # signal fold, admission posture, and the transition history
        if self._overload is not None:
            body["overload"] = self._overload.status()
        if self._obs_incidents is not None:
            body["incidents"] = self._obs_incidents.counters()
        return web.json_response(body)

    def _durability_status(self) -> Optional[dict]:
        """Durability section of /statusz (ISSUE 7): retained snapshot
        generations (quarantined ones included — they are the evidence),
        the WAL coverage window [floor, head], boot-restore fallback
        tallies, and the scrubber's last-pass summary. Blocking
        filesystem reads — call via ``asyncio.to_thread``."""
        ckpt = getattr(self.storage, "checkpoint_dir", None)
        scrubber = getattr(self.storage, "scrubber", None)
        wal = getattr(self.storage, "wal", None)
        if not ckpt and scrubber is None and wal is None:
            return None
        out: dict = {}
        if ckpt:
            from zipkin_tpu.tpu import snapshot as snap_mod

            out["generations"] = snap_mod.generation_status(ckpt)
            floor = snap_mod.retained_coverage(ckpt)
            out["walCoverage"] = {
                "floor": floor,
                "head": int(getattr(self.storage.agg, "wal_seq", 0)),
            }
        restore = getattr(self.storage, "restore_stats", None)
        if restore:
            out["restore"] = {
                name: restore[name]
                for name in (
                    "restoreFallbacks", "generationsQuarantined",
                    "walReplayBatches", "restoreMs",
                )
                if name in restore
            }
        if scrubber is not None:
            out["scrub"] = scrubber.status()
        return out

    async def get_ui_config(self, request: web.Request) -> web.Response:
        return web.json_response(
            {
                "environment": "",
                "queryLimit": self.config.query_limit,
                "defaultLookback": self.config.default_lookback,
                "searchEnabled": self.config.search_enabled,
                "autocompleteKeys": list(self.config.autocomplete_keys),
                "dependency": {"enabled": True},
            }
        )


def _snake(name: str) -> str:
    out = []
    for ch in name:
        if ch.isupper():
            out.append("_")
            out.append(ch.lower())
        else:
            out.append(ch)
    return "".join(out)


def _prom_name(name: str) -> str:
    """Sanitize to the Prometheus metric-name charset ``[a-zA-Z0-9_:]``,
    mapping every other rune (dots included) to ``_`` — real scrapers
    reject the exposition otherwise."""
    out = "".join(
        ch if (ch.isascii() and (ch.isalnum() or ch in "_:")) else "_"
        for ch in name
    )
    if not out or out[0].isdigit():
        out = "_" + out
    return out


def _prom_label(value) -> str:
    """Escape a label value per the exposition format."""
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace("\n", "\\n")
        .replace('"', '\\"')
    )


def _prom_stage_histograms(snap, slow_events=None) -> List[str]:
    """Flight-recorder stage latencies as one native histogram family.

    Log2-µs buckets become cumulative ``le`` bounds in seconds (the
    exact inclusive bucket bound, ``(2^b - 1)/1e6``); only non-empty
    buckets are emitted — cumulative series stay valid when sparse.

    When the slow-event ring is passed, bucket lines carry OpenMetrics
    exemplars pointing at the self-span trace id of an over-budget
    observation that landed in that bucket — a burning latency SLO
    links straight to a retrievable pipeline trace. Exemplar syntax
    (``# {trace_id="..."} <seconds>``) is an OpenMetrics extension that
    classic text-format parsers treat as a comment, so the exposition
    stays valid for both.
    """
    stats = snap.nonzero()
    if not stats:
        return []
    # newest exemplar per (stage, bucket): the ring is oldest-first and
    # only self-span-enriched events carry a trace id worth linking
    by_bucket: Dict[Tuple[str, int], Tuple[str, float]] = {}
    for ev in slow_events or ():
        trace_id = ev.get("traceId")
        if not trace_id:
            continue
        dur_us = int(ev.get("durUs", 0))
        by_bucket[(ev["stage"], max(dur_us, 0).bit_length())] = (
            trace_id, dur_us / 1e6,
        )
    fam = "zipkin_tpu_stage_latency_seconds"
    lines = [
        f"# HELP {fam} Pipeline stage latency (log2 microsecond buckets).",
        f"# TYPE {fam} histogram",
    ]
    for st in stats:
        cum = 0
        for b, count in enumerate(st.buckets[:-1]):
            if not count:
                continue
            cum += count
            le = obs.bucket_le_us(b) / 1e6
            line = f'{fam}_bucket{{stage="{st.stage}",le="{le}"}} {cum}'
            ex = by_bucket.get((st.stage, b))
            if ex is not None:
                line += f' # {{trace_id="{_prom_label(ex[0])}"}} {ex[1]}'
            lines.append(line)
        lines.append(f'{fam}_bucket{{stage="{st.stage}",le="+Inf"}} {st.count}')
        lines.append(f'{fam}_sum{{stage="{st.stage}"}} {st.sum_us / 1e6}')
        lines.append(f'{fam}_count{{stage="{st.stage}"}} {st.count}')
    return lines


def _prom_accuracy(status) -> List[str]:
    """Per-service digest-error families from the accuracy observatory.
    The scalar gauges (worst-service, HLL, recall, retention bias) ride
    the flat ``zipkin_tpu_accuracy_*`` render in ``get_prometheus``;
    only the service-labelled detail needs its own exposition."""
    rows = status.get("services") or []
    if not rows:
        return []
    lines: List[str] = []
    fields = (
        ("p50RelErr", "p50_relerr", "digest p50 relative error"),
        ("p99RelErr", "p99_relerr", "digest p99 relative error"),
        ("p99Bound", "p99_bound", "stated p99 confidence bound"),
    )
    for field, suffix, help_text in fields:
        fam = f"zipkin_tpu_accuracy_service_{suffix}"
        lines.append(
            f"# HELP {fam} Per-service {help_text} (device vs shadow)."
        )
        lines.append(f"# TYPE {fam} gauge")
        for row in rows:
            lines.append(
                f'{fam}{{service="{_prom_label(row["service"])}"}} '
                f'{row[field]}'
            )
    return lines


def _prom_mp_workers(table) -> List[str]:
    """Fan-out tier per-worker attribution as labelled counter families
    (``worker="<widx>"``). The nested ``mpWorkerTable`` is skipped by the
    flat-gauge loop; this is its exposition-format rendering."""
    if not table:
        return []
    lines: List[str] = []
    fields = (
        ("chunks", "chunks dispatched"),
        ("spans", "spans parsed"),
        ("payloads", "payloads completed"),
        ("parseUs", "parse wall microseconds"),
        ("packUs", "pack wall microseconds"),
        ("routeUs", "route wall microseconds"),
        ("fallbacks", "inline-fallback payloads"),
    )
    for field, help_text in fields:
        fam = _prom_name(f"zipkin_tpu_mp_worker_{_snake(field)}_total")
        lines.append(f"# HELP {fam} Ingest worker {help_text}.")
        lines.append(f"# TYPE {fam} counter")
        for row in table:
            lines.append(
                f'{fam}{{worker="{_prom_label(row["widx"])}"}} {row[field]}'
            )
    # instantaneous queue posture (ISSUE 11 satellite): depth is live
    # occupancy, high-water the worst since boot — gauges, not counters
    gauges = (
        ("queueDepth", "live bounded-queue depth (payloads in flight)"),
        ("queueHighWater", "bounded-queue depth high-water mark"),
    )
    for field, help_text in gauges:
        fam = _prom_name(f"zipkin_tpu_mp_worker_{_snake(field)}")
        lines.append(f"# HELP {fam} Ingest worker {help_text}.")
        lines.append(f"# TYPE {fam} gauge")
        for row in table:
            lines.append(
                f'{fam}{{worker="{_prom_label(row["widx"])}"}} '
                f'{row.get(field, 0)}'
            )
    return lines


def _prom_critpath(segments) -> List[str]:
    """Critical-path segment families from the stitcher's fold
    aggregates. The scalar gauges (timelines, lambda, occupancy,
    saturation, conservation) ride the flat ``zipkin_tpu_critpath_*``
    render; the per-segment table needs segment+kind labels."""
    if not segments:
        return []
    lines: List[str] = []
    fields = (
        ("count", "folded occurrences", "counter", "_total"),
        ("sumUs", "cumulative wall microseconds", "counter", "_total"),
        ("maxUs", "worst single occurrence microseconds", "gauge", ""),
    )
    for field, help_text, typ, suffix in fields:
        fam = _prom_name(f"zipkin_tpu_critpath_segment_{_snake(field)}{suffix}")
        lines.append(f"# HELP {fam} Critical-path segment {help_text}.")
        lines.append(f"# TYPE {fam} {typ}")
        for seg, row in sorted(segments.items()):
            lines.append(
                f'{fam}{{segment="{_prom_label(seg)}",'
                f'kind="{_prom_label(row["kind"])}"}} {row[field]}'
            )
    return lines


def _prom_query_lock(table) -> List[str]:
    """Aggregator-lock contention ledger (ISSUE 12): native wait/hold
    histogram families plus per-label holder attribution. The scalar
    ``zipkin_tpu_query_lock_*`` gauges (acquisitions, waiters,
    high-water, p50/p99) ride the flat render; the histograms and the
    holder table need their own families."""
    if not table:
        return []
    lines: List[str] = []
    hists = (
        ("wait", table.get("waitHist"), table.get("waitSumUs", 0),
         "time a thread waited to acquire the aggregator lock"),
        ("hold", table.get("holdHist"), table.get("holdSumUs", 0),
         "time an outermost acquire held the aggregator lock"),
    )
    for which, hist, sum_us, help_text in hists:
        if not hist or not sum(hist):
            continue
        fam = f"zipkin_tpu_query_lock_{which}_seconds"
        lines.append(f"# HELP {fam} Lock ledger: {help_text}.")
        lines.append(f"# TYPE {fam} histogram")
        total = sum(hist)
        cum = 0
        for b, count in enumerate(hist[:-1]):
            if not count:
                continue
            cum += count
            le = obs.bucket_le_us(b) / 1e6
            lines.append(f'{fam}_bucket{{le="{le}"}} {cum}')
        lines.append(f'{fam}_bucket{{le="+Inf"}} {total}')
        lines.append(f'{fam}_sum {sum_us / 1e6}')
        lines.append(f'{fam}_count {total}')
    holders = table.get("holders") or {}
    if holders:
        count_fam = "zipkin_tpu_query_lock_holds_total"
        sum_fam = "zipkin_tpu_query_lock_hold_sum_us_total"
        lines.append(
            f"# HELP {count_fam} Outermost lock holds by holder label."
        )
        lines.append(f"# TYPE {count_fam} counter")
        for label, row in sorted(holders.items()):
            lines.append(
                f'{count_fam}{{holder="{_prom_label(label)}"}} '
                f'{row["count"]}'
            )
        lines.append(
            f"# HELP {sum_fam} Cumulative hold microseconds by holder "
            "label."
        )
        lines.append(f"# TYPE {sum_fam} counter")
        for label, row in sorted(holders.items()):
            lines.append(
                f'{sum_fam}{{holder="{_prom_label(label)}"}} '
                f'{row["holdSumUs"]}'
            )
    return lines


def _prom_query_segments(segments) -> List[str]:
    """Per-segment query critical-path aggregates, mirroring the
    critpath segment families with segment+kind labels."""
    if not segments:
        return []
    lines: List[str] = []
    fields = (
        ("count", "folded occurrences", "counter", "_total"),
        ("sumUs", "cumulative wall microseconds", "counter", "_total"),
        ("maxUs", "worst single occurrence microseconds", "gauge", ""),
    )
    for field, help_text, typ, suffix in fields:
        fam = _prom_name(f"zipkin_tpu_query_segment_{_snake(field)}{suffix}")
        lines.append(f"# HELP {fam} Query critical-path segment "
                     f"{help_text}.")
        lines.append(f"# TYPE {fam} {typ}")
        for seg, row in sorted(segments.items()):
            lines.append(
                f'{fam}{{segment="{_prom_label(seg)}",'
                f'kind="{_prom_label(row["kind"])}"}} {row[field]}'
            )
    return lines


def _prom_overload(status) -> List[str]:
    """Overload control plane families (ISSUE 13). Scalars carry the
    ladder posture; the per-signal family shows WHICH bottleneck is
    driving the load index (it is a MAX fold, so exactly one signal is
    the story at any instant)."""
    lines: List[str] = []
    gauges = (
        ("level", status["level"],
         "Brownout ladder level (0=B0 normal .. 3=B3 essential-only)"),
        ("load_index", status["loadIndex"],
         "EMA-smoothed load index (max-folded signal pressure)"),
        ("raw_load_index", status["rawLoadIndex"],
         "Unsmoothed load index from the latest tick"),
        ("bulk_admit_p", status["bulkAdmitP"],
         "Bulk-class ingest admit probability (1.0 outside B2)"),
    )
    for suffix, value, help_text in gauges:
        fam = f"zipkin_tpu_overload_{suffix}"
        lines.append(f"# HELP {fam} {help_text}.")
        lines.append(f"# TYPE {fam} gauge")
        lines.append(f"{fam} {value}")
    signals = status.get("signals") or {}
    if signals:
        fam = "zipkin_tpu_overload_signal"
        lines.append(
            f"# HELP {fam} Per-signal pressure ratio "
            "(value over design limit; 1.0 = at the limit)."
        )
        lines.append(f"# TYPE {fam} gauge")
        for name, value in sorted(signals.items()):
            lines.append(
                f'{fam}{{signal="{_prom_label(name)}"}} {value}'
            )
    counters = status.get("counters") or {}
    counter_fields = (
        ("admitted", "admitted_total", "payloads admitted"),
        ("admittedEssential", "admitted_essential_total",
         "error-class payloads admitted under brownout"),
        ("shedBulk", "shed_bulk_total", "bulk-class payloads shed"),
        ("shedTotal", "shed_total", "payloads shed"),
        ("deadlineExpired", "deadline_expired_total",
         "requests dropped already past their deadline"),
        ("transitions", "transitions_total", "ladder level transitions"),
    )
    for field, suffix, help_text in counter_fields:
        if field not in counters:
            continue
        fam = f"zipkin_tpu_overload_{suffix}"
        lines.append(f"# HELP {fam} Overload controller: {help_text}.")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {counters[field]}")
    return lines


def _prom_tenants(status) -> List[str]:
    """Per-tenant admission families (ISSUE 18): every family carries a
    ``{tenant=}`` label, so one flooding tenant's shed curve is
    separable from everyone else's flat zero on the same graph. The
    label values come from ``normalize_tenant``'s bounded alphabet, so
    they are prometheus-label-safe by construction; the row count is
    bounded by the admission table's LRU cap."""
    tenants = (status or {}).get("tenants")
    if not tenants:
        return []
    lines: List[str] = []
    table = tenants.get("tenants") or {}
    scalars = (
        ("table_size", len(table),
         "Live tenants in the bounded admission table", "gauge"),
        ("evictions_total", tenants.get("evictions", 0),
         "Tenant rows LRU-evicted from the admission table", "counter"),
    )
    for suffix, value, help_text, typ in scalars:
        fam = f"zipkin_tpu_tenant_{suffix}"
        lines.append(f"# HELP {fam} {help_text}.")
        lines.append(f"# TYPE {fam} {typ}")
        lines.append(f"{fam} {value}")
    fields = (
        ("level", "level",
         "Per-tenant brownout level (0=admit .. 3=essential-only)",
         "gauge"),
        ("pressure", "pressure",
         "Per-tenant demand pressure EMA (offered rate over budget)",
         "gauge"),
        ("offered", "offered_total", "payloads offered", "counter"),
        ("admitted", "admitted_total", "payloads admitted", "counter"),
        ("shed", "shed_total", "payloads shed (scope=tenant)", "counter"),
        ("retainedSpans", "retained_spans_total",
         "spans retained past sampling", "counter"),
    )
    for field, suffix, help_text, typ in fields:
        fam = f"zipkin_tpu_tenant_{suffix}"
        if typ == "counter":
            lines.append(f"# HELP {fam} Tenant admission: {help_text}.")
        else:
            lines.append(f"# HELP {fam} {help_text}.")
        lines.append(f"# TYPE {fam} {typ}")
        for name, row in sorted(table.items()):
            lines.append(
                f'{fam}{{tenant="{_prom_label(name)}"}} {row[field]}'
            )
    return lines


def _prom_slo(verdicts) -> List[str]:
    """SLO watchdog families: one boolean alert gauge per SLO plus the
    multi-window burn rates it was computed from."""
    if not verdicts:
        return []
    alert_fam = "zipkin_tpu_slo_alert"
    burn_fam = "zipkin_tpu_slo_burn_rate"
    lines = [
        f"# HELP {alert_fam} SLO burn-rate alert (1 = burning).",
        f"# TYPE {alert_fam} gauge",
    ]
    for v in verdicts:
        lines.append(
            f'{alert_fam}{{slo="{_prom_label(v["name"])}"}} {int(v["alert"])}'
        )
    lines.append(
        f"# HELP {burn_fam} Error-budget burn rate per evaluation window."
    )
    lines.append(f"# TYPE {burn_fam} gauge")
    for v in verdicts:
        for wname, wv in sorted(v["windows"].items()):
            lines.append(
                f'{burn_fam}{{slo="{_prom_label(v["name"])}",'
                f'window="{_prom_label(wname)}"}} {wv["burn"]}'
            )
    return lines


def parse_annotation_query(raw: Optional[str]) -> Dict[str, str]:
    """Parse ``"error and http.method=GET"`` into ``{error: '', http.method:
    'GET'}`` — the upstream annotationQuery grammar."""
    out: Dict[str, str] = {}
    if not raw:
        return out
    for token in raw.split(" and "):
        token = token.strip()
        if not token:
            continue
        key, sep, value = token.partition("=")
        out[key] = value if sep else ""
    return out


async def run_server(config: Optional[ServerConfig] = None) -> None:
    server = ZipkinServer(config or ServerConfig.from_env())
    await server.start()
    try:
        await asyncio.Event().wait()
    finally:
        await server.stop()
