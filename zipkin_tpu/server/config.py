"""Server configuration: a dataclass tree overridable by environment vars.

Reference semantics: ``zipkin-server/src/main/resources/zipkin-server-
shared.yml`` (SURVEY.md §2.4, §5) — the same env var names are honored where
they exist upstream (``STORAGE_TYPE``, ``QUERY_PORT``, ``QUERY_LOOKBACK``,
``COLLECTOR_SAMPLE_RATE``, ``SEARCH_ENABLED``, ``AUTOCOMPLETE_KEYS``,
``STRICT_TRACE_ID``, ``MEM_MAX_SPANS``…), plus TPU-tier knobs that are new
here (``TPU_*``).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Sequence, Tuple


def _env_bool(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return int(raw) if raw else default


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return float(raw) if raw else default


def _bounded(name: str, value: int, lo: int, hi: int, *, allow_zero: bool = False) -> int:
    # refuse-to-boot posture for structural knobs: a reader fleet or shm
    # segment sized from a typo'd env var should fail loudly at config
    # time, not OOM or spin at runtime
    if allow_zero and value == 0:
        return value
    if not (lo <= value <= hi):
        raise ValueError(
            f"{name}={value} out of bounds [{lo}, {hi}]"
            + (" (0 = disabled)" if allow_zero else "")
        )
    return value


def _env_list(name: str) -> Tuple[str, ...]:
    raw = os.environ.get(name, "")
    return tuple(x.strip() for x in raw.split(",") if x.strip())


DAY_MS = 86_400_000


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    host: str = "0.0.0.0"
    port: int = 9411
    storage_type: str = "mem"  # mem | tpu
    strict_trace_id: bool = True
    search_enabled: bool = True
    autocomplete_keys: Sequence[str] = ()
    mem_max_spans: int = 500_000
    default_lookback: int = 7 * DAY_MS  # QUERY_LOOKBACK, ms
    query_limit: int = 10
    sample_rate: float = 1.0
    http_collector_enabled: bool = True
    grpc_collector_enabled: bool = False
    grpc_port: int = 9412
    scribe_enabled: bool = False
    scribe_port: int = 9410
    throttle_enabled: bool = False
    throttle_max_concurrency: int = 8
    self_tracing_enabled: bool = False
    self_tracing_sample_rate: float = 1.0
    # slow-dispatch self-spans (zipkin_tpu.obs): over-budget pipeline
    # stages are published as internal spans for zipkin-tpu-pipeline
    # through the collector path. Opt-in like self-tracing — the spans
    # land in the server's own store. TPU_OBS_BUDGET_SCALE scales every
    # stage budget (0.0 = everything is "slow"; dogfood/debug posture).
    obs_selfspans_enabled: bool = False
    obs_budget_scale: float = 1.0
    # windowed telemetry plane (zipkin_tpu.obs.windows): per-tick delta
    # rings over the flight recorder + store counters, serving windowed
    # quantiles/rates on /statusz and feeding the SLO watchdog. The
    # ticker thread runs with the server lifecycle; reads also catch up
    # lazily, so embedders that never start() still get fresh windows.
    obs_windows_enabled: bool = True
    obs_windows_tick_s: float = 1.0
    # SLO burn-rate watchdog (zipkin_tpu.obs.slo): multi-window burn
    # evaluation of the default spec set; alerts ride /metrics,
    # /prometheus and the statusz slo section
    obs_slo_enabled: bool = True
    obs_slo_short_s: float = 60.0
    obs_slo_long_s: float = 300.0
    obs_slo_burn_threshold: float = 2.0
    # accuracy observatory (zipkin_tpu.obs.shadow + obs.accuracy): a
    # bounded-memory host shadow of the ingest stream whose exact
    # sub-stream statistics anchor live relative-error gauges for the
    # device sketches (digest p50/p99, HLL, link recall, retention
    # bias). TPU_OBS_SHADOW gates the whole plane (requires the
    # windowed plane; TPU storage only). Knobs:
    #   TPU_OBS_SHADOW_RESERVOIR   exact durations kept per service —
    #                              quantile rank noise ~ 1/sqrt(k)
    #                              (512 => +-4.4% p99 rank at 3 sigma)
    #   TPU_OBS_SHADOW_DISTINCT    trace ids kept by the adaptive
    #                              distinct sketch — HLL-oracle rel.
    #                              stderr ~ 1.2/sqrt(k)
    #   TPU_OBS_SHADOW_LINK_RATE   fraction of traces whose spans are
    #                              retained whole for the dependency-
    #                              recall oracle (trace-affine hash)
    #   TPU_OBS_SHADOW_ROLLUP_S    estimator cadence (device reads ride
    #                              the one-transfer read path)
    #   TPU_OBS_SHADOW_PENDING     max buffered ingest batches; overflow
    #                              drops oldest and degrades the plane
    #                              to "no signal" via coverage gating
    # ingest critical-path tracer (zipkin_tpu.obs.critpath): chunk-scoped
    # wire-to-durable timelines stitched from a shared-memory interval
    # ledger across the MP fan-out. TPU_OBS_CRITPATH gates the plane
    # (active only when the MP tier runs); TPU_OBS_CRITPATH_SLOTS sizes
    # the ledger (one slot per in-flight chunk; overflow degrades to
    # untraced, counted critpathSkipped). TPU_OBS_CRITPATH_RECLAIM_S is
    # the stale-slot reclaim age (a SIGKILL'd worker's orphaned slot is
    # abandoned after this long so timelines cannot wedge).
    obs_critpath_enabled: bool = True
    obs_critpath_slots: int = 256
    obs_critpath_reclaim_s: float = 60.0
    obs_shadow_enabled: bool = True
    obs_shadow_reservoir_k: int = 512
    obs_shadow_distinct_k: int = 4096
    obs_shadow_link_rate: float = 0.125
    obs_shadow_rollup_s: float = 5.0
    obs_shadow_pending_max: int = 512
    # query-plane observatory (zipkin_tpu.obs.querytrace): per-query
    # critical-path traces + the aggregator-lock contention ledger.
    # TPU_OBS_QUERY gates both. Incident capture (zipkin_tpu.obs.
    # incidents): when TPU_OBS_INCIDENT_DIR names a directory, every SLO
    # trip snapshots the volatile observability planes into a bounded-
    # retention JSON bundle there (TPU_OBS_INCIDENT_RETENTION newest
    # kept; a flapping SLO cannot fill the disk).
    obs_query_enabled: bool = True
    obs_incident_dir: str = ""
    obs_incident_retention: int = 16
    # TPU aggregation tier
    tpu_devices: Optional[int] = None  # None = all visible
    tpu_batch_size: int = 8192
    tpu_fast_ingest: bool = False  # line-rate JSON->device path
    tpu_fast_archive_sample: int = 64  # 1/N traces archived in fast mode
    tpu_mp_workers: int = 0  # >0: multi-process parse tier (mp_ingest)
    # per-worker payload bound of the fan-out tier's queues: when every
    # live worker's queue is full the boundary answers HTTP 429 / gRPC
    # RESOURCE_EXHAUSTED (carrying Retry-After / retry-delay backoff
    # guidance from the overload controller — queue-full rejection is
    # the LAST backpressure surface, behind brownout admission and
    # sampling-budget tightening; see runtime/overload.py)
    tpu_mp_queue_depth: int = 2
    # span-ring stripe depth per worker (tpu/ring.py, ISSUE 16): slots
    # the dispatcher may lag behind each worker before ring occupancy
    # pushes back on submit(); 0 = derive (max(4, 2 * queue slots))
    tpu_mp_ring_slots: int = 0
    # chunks one dispatcher flush may coalesce into a single remap +
    # jitted step + WAL record; 1 = per-chunk dispatch (pre-ring parity)
    tpu_mp_coalesce_max: int = 8
    # overload control plane (runtime/overload.py, ISSUE 13): folds the
    # published pressure signals into a hysteretic load index driving
    # the B0->B3 brownout ladder — B1 sheds expensive observability and
    # serves reads cache-first within TPU_OVERLOAD_MAX_STALE_MS, B2
    # sheds bulk ingest probabilistically (error-class traffic always
    # admits) and tightens the sampling budget, B3 serves cached-only
    # reads and essential ingest only. Thresholds are the ladder's
    # enter edges; exit subtracts TPU_OVERLOAD_EXIT_MARGIN with a
    # TPU_OVERLOAD_DWELL_TICKS minimum dwell (hysteresis).
    overload_enabled: bool = True
    overload_enter_b1: float = 0.70
    overload_enter_b2: float = 0.85
    overload_enter_b3: float = 0.95
    overload_exit_margin: float = 0.10
    overload_dwell_ticks: int = 5
    overload_max_stale_ms: int = 5000
    overload_retry_base_s: float = 0.25
    # tenant-isolated admission (runtime/tenant.py, ISSUE 18): every
    # payload is attributed to the tenant named by X-Tenant-Id (HTTP) /
    # x-tenant-id (gRPC metadata); absent or hostile ids collapse to the
    # "default" tenant. When TPU_TENANT_INGEST_BYTES_PER_S > 0 each
    # tenant gets its own token bucket over ingest bytes/sec (burst =
    # rate * TPU_TENANT_INGEST_BURST_S) and a per-tenant brownout level:
    # a flooding tenant is shed with tenant-scoped Retry-After guidance
    # while every other tenant — and the GLOBAL ladder — stays at B0.
    # TPU_TENANT_RETAINED_SPANS_PER_S (0 = off) adds a second budget
    # over retained spans/sec, charged at dispatcher ack time through
    # the sampling tier's per-tenant budget table. The tenant table is
    # bounded (TPU_TENANT_MAX, LRU-evicted, evictions counted) so a
    # hostile id stream cannot grow server state. TPU_TENANT_SLO lists
    # tenants that get their own shed-ratio SloSpec instances.
    tenant_enabled: bool = True
    tenant_max: int = 64
    tenant_ingest_bytes_per_s: float = 0.0
    tenant_ingest_burst_s: float = 2.0
    tenant_retained_spans_per_s: float = 0.0
    tenant_flood_ratio: float = 2.0
    tenant_dwell_ticks: int = 3
    tenant_slo_tenants: Tuple[str, ...] = ()
    # epoch-published read mirror (tpu/mirror.py, ISSUE 14): the windows
    # ticker republishes the packed read-program outputs once per tick
    # (one aggregator-lock hold per epoch) and the query entrypoints
    # serve lock-free from the published snapshot by default.
    # TPU_READ_MIRROR=false reverts every read to the lock path;
    # TPU_MIRROR_MAX_STALE_MS is the published staleness contract — the
    # oldest answer the mirror may serve without a per-request override
    # (the staleness_ms query param loosens/tightens per request; <= 0
    # forces a fresh read), and the bound the query_mirror_staleness
    # SLO pages on.
    tpu_read_mirror: bool = True
    tpu_mirror_max_stale_ms: int = 5000
    # scale-out read serving (zipkin_tpu.serving, ISSUE 19): when
    # TPU_MIRROR_SEGMENT_BYTES > 0 the mirror publisher also serializes
    # each epoch into a double-buffered shared-memory segment that
    # stateless reader processes (python -m zipkin_tpu.serving) map
    # read-only and serve from without ever touching the aggregator
    # lock. TPU_READERS sizes the per-reader heartbeat/demand stripes
    # the segment is created with (and is the reader-count default the
    # serving front end inherits); TPU_READER_PORT_BASE is the first
    # reader's HTTP port (reader rN listens on base+N, the supervisor's
    # aggregate endpoint on base-1).
    tpu_readers: int = 4
    tpu_mirror_segment_bytes: int = 0
    tpu_reader_port_base: int = 9512
    # deadline propagation (ISSUE 13): honor gRPC deadlines and the
    # X-Request-Timeout-Ms HTTP header at ingest + query entrypoints —
    # work already past its deadline is dropped before device dispatch
    # (counted deadlineExpired, never dispatched)
    deadline_propagation_enabled: bool = True
    # one-knob durable boot (ISSUE 3): TPU_RESUME_DIR=<dir> defaults
    # checkpoint/WAL/archive under <dir>/{snap,wal,archive} so boot runs
    # the full restore sequence — snapshot restore, WAL-tail replay,
    # transport offset resume — without wiring three dirs by hand. The
    # individual TPU_CHECKPOINT_DIR / TPU_WAL_DIR / TPU_ARCHIVE_DIR
    # knobs still override their piece when both are set.
    tpu_resume_dir: Optional[str] = None
    tpu_checkpoint_dir: Optional[str] = None
    tpu_wal_dir: Optional[str] = None  # append-log of fused batches (tpu/wal.py)
    # disk-backed raw-span archive (tpu/archive.py): every ingested
    # span's raw JSON retained behind a trace-id index; retention is the
    # byte budget (oldest segments dropped whole)
    tpu_archive_dir: Optional[str] = None
    tpu_archive_max_bytes: int = 2 << 30
    tpu_archive_segment_bytes: int = 64 << 20
    # fsync each WAL append: durability vs host/power failure, at a
    # per-batch fsync cost. Off = page-cache durability (process crash
    # only — the kill -9 soak's boundary; see ARCHITECTURE.md).
    tpu_wal_fsync: bool = False
    # periodic snapshot cadence (bounds WAL growth + crash-replay
    # window); active only when a checkpoint dir is configured. 0 = off.
    tpu_snapshot_interval_s: float = 300.0
    # bit-rot tolerance (ISSUE 7): how many intact snapshot generations
    # a commit retains (the fallback depth — a digest mismatch
    # quarantines the bad generation and restores the previous one),
    # and the background at-rest CRC scrubber's cadence + read-bandwidth
    # pacing. TPU_SCRUB_INTERVAL_S=0 disables scrubbing.
    tpu_snapshot_keep: int = 2
    tpu_scrub_interval_s: float = 300.0
    tpu_scrub_bytes_per_sec: int = 8 << 20
    # adaptive tail-sampling tier (zipkin_tpu.sampling): device-side
    # keep/drop verdicts gate WAL/archive/ring retention while sketches
    # keep seeing 100% of spans. TPU_SAMPLING=true arms the tier;
    # TPU_SAMPLING_BUDGET (retained spans/sec, 0 = no controller) drives
    # the per-service adaptive rate controller — under overload it
    # tightens rates instead of the throttle shedding at the door.
    tpu_sampling: bool = False
    tpu_sampling_budget: float = 0.0
    tpu_sampling_interval_s: float = 5.0
    tpu_sampling_min_rate: int = 256
    tpu_sampling_tail_quantile: float = 0.99
    tpu_sampling_rare_min: int = 4
    # device state shape (see zipkin_tpu.tpu.state.AggConfig); None =
    # AggConfig's default for that field
    tpu_agg: dict = dataclasses.field(default_factory=dict)

    @staticmethod
    def from_env() -> "ServerConfig":
        # Archive default posture (decided r5, VERDICT r4 order 2): the
        # reference keeps every ingested span queryable by default, so
        # FAST mode defaults the disk archive ON (budget-bounded) rather
        # than silently serving a 1-in-64 trace sample. TPU_ARCHIVE_DIR
        # sets the directory; "off"/"none"/"0" disables explicitly;
        # unset + fast ingest -> ./zipkin-tpu-archive. Object-path-only
        # servers (TPU_FAST_INGEST unset) already retain every span in
        # the bounded RAM store, the reference's mem posture, so they
        # stay disk-free by default.
        fast_ingest = _env_bool("TPU_FAST_INGEST", False)
        raw_resume = os.environ.get("TPU_RESUME_DIR") or None
        resume_dir = os.path.abspath(raw_resume) if raw_resume else None
        raw_archive = os.environ.get("TPU_ARCHIVE_DIR")
        if raw_archive and raw_archive.lower() in ("off", "none", "0"):
            archive_dir = None
        elif raw_archive:
            archive_dir = raw_archive
        elif resume_dir:
            # the resume dir's contract is "everything durable lives
            # here": the raw-span archive rides along so a restarted
            # server still serves complete traces for pre-crash ids
            archive_dir = os.path.join(resume_dir, "archive")
        elif fast_ingest:
            # absolute, so a restart from a different cwd finds the
            # same archive instead of silently orphaning it; the server
            # logs the resolved path at boot, and storage construction
            # degrades to archive-free (with a warning) when the path
            # is unwritable rather than refusing to boot
            archive_dir = os.path.abspath("zipkin-tpu-archive")
        else:
            archive_dir = None
        return ServerConfig(
            host=os.environ.get("QUERY_HOST", "0.0.0.0"),
            port=_env_int("QUERY_PORT", 9411),
            storage_type=os.environ.get("STORAGE_TYPE", "mem"),
            strict_trace_id=_env_bool("STRICT_TRACE_ID", True),
            search_enabled=_env_bool("SEARCH_ENABLED", True),
            autocomplete_keys=_env_list("AUTOCOMPLETE_KEYS"),
            mem_max_spans=_env_int("MEM_MAX_SPANS", 500_000),
            default_lookback=_env_int("QUERY_LOOKBACK", 7 * DAY_MS),
            query_limit=_env_int("QUERY_LIMIT", 10),
            sample_rate=_env_float("COLLECTOR_SAMPLE_RATE", 1.0),
            http_collector_enabled=_env_bool("COLLECTOR_HTTP_ENABLED", True),
            grpc_collector_enabled=_env_bool("COLLECTOR_GRPC_ENABLED", False),
            grpc_port=_env_int("COLLECTOR_GRPC_PORT", 9412),
            scribe_enabled=_env_bool("COLLECTOR_SCRIBE_ENABLED", False),
            scribe_port=_env_int("COLLECTOR_SCRIBE_PORT", 9410),
            throttle_enabled=_env_bool("STORAGE_THROTTLE_ENABLED", False),
            throttle_max_concurrency=_env_int("STORAGE_THROTTLE_MAX_CONCURRENCY", 8),
            self_tracing_enabled=_env_bool("SELF_TRACING_ENABLED", False),
            self_tracing_sample_rate=_env_float("SELF_TRACING_SAMPLE_RATE", 1.0),
            obs_selfspans_enabled=_env_bool("TPU_OBS_SELFSPANS", False),
            obs_budget_scale=_env_float("TPU_OBS_BUDGET_SCALE", 1.0),
            obs_windows_enabled=_env_bool("TPU_OBS_WINDOWS", True),
            obs_windows_tick_s=_env_float("TPU_OBS_TICK_S", 1.0),
            obs_slo_enabled=_env_bool("TPU_SLO", True),
            obs_slo_short_s=_env_float("TPU_SLO_SHORT_S", 60.0),
            obs_slo_long_s=_env_float("TPU_SLO_LONG_S", 300.0),
            obs_slo_burn_threshold=_env_float("TPU_SLO_BURN", 2.0),
            obs_critpath_enabled=_env_bool("TPU_OBS_CRITPATH", True),
            obs_critpath_slots=_env_int("TPU_OBS_CRITPATH_SLOTS", 256),
            obs_critpath_reclaim_s=_env_float(
                "TPU_OBS_CRITPATH_RECLAIM_S", 60.0
            ),
            obs_shadow_enabled=_env_bool("TPU_OBS_SHADOW", True),
            obs_shadow_reservoir_k=_env_int("TPU_OBS_SHADOW_RESERVOIR", 512),
            obs_shadow_distinct_k=_env_int("TPU_OBS_SHADOW_DISTINCT", 4096),
            obs_shadow_link_rate=_env_float("TPU_OBS_SHADOW_LINK_RATE", 0.125),
            obs_shadow_rollup_s=_env_float("TPU_OBS_SHADOW_ROLLUP_S", 5.0),
            obs_shadow_pending_max=_env_int("TPU_OBS_SHADOW_PENDING", 512),
            obs_query_enabled=_env_bool("TPU_OBS_QUERY", True),
            obs_incident_dir=os.environ.get("TPU_OBS_INCIDENT_DIR", ""),
            obs_incident_retention=_env_int(
                "TPU_OBS_INCIDENT_RETENTION", 16
            ),
            tpu_devices=_env_int("TPU_DEVICES", 0) or None,
            tpu_batch_size=_env_int("TPU_BATCH_SIZE", 8192),
            tpu_fast_ingest=fast_ingest,
            tpu_fast_archive_sample=_env_int("TPU_FAST_ARCHIVE_SAMPLE", 64),
            tpu_mp_workers=_env_int("TPU_MP_WORKERS", 0),
            tpu_mp_queue_depth=_env_int("TPU_MP_QUEUE_DEPTH", 2),
            tpu_mp_ring_slots=_env_int("TPU_MP_RING_SLOTS", 0),
            tpu_mp_coalesce_max=_env_int("TPU_MP_COALESCE_MAX", 8),
            overload_enabled=_env_bool("TPU_OVERLOAD", True),
            overload_enter_b1=_env_float("TPU_OVERLOAD_ENTER_B1", 0.70),
            overload_enter_b2=_env_float("TPU_OVERLOAD_ENTER_B2", 0.85),
            overload_enter_b3=_env_float("TPU_OVERLOAD_ENTER_B3", 0.95),
            overload_exit_margin=_env_float("TPU_OVERLOAD_EXIT_MARGIN", 0.10),
            overload_dwell_ticks=_env_int("TPU_OVERLOAD_DWELL_TICKS", 5),
            overload_max_stale_ms=_env_int("TPU_OVERLOAD_MAX_STALE_MS", 5000),
            overload_retry_base_s=_env_float(
                "TPU_OVERLOAD_RETRY_BASE_S", 0.25
            ),
            tenant_enabled=_env_bool("TPU_TENANT", True),
            tenant_max=_env_int("TPU_TENANT_MAX", 64),
            tenant_ingest_bytes_per_s=_env_float(
                "TPU_TENANT_INGEST_BYTES_PER_S", 0.0
            ),
            tenant_ingest_burst_s=_env_float(
                "TPU_TENANT_INGEST_BURST_S", 2.0
            ),
            tenant_retained_spans_per_s=_env_float(
                "TPU_TENANT_RETAINED_SPANS_PER_S", 0.0
            ),
            tenant_flood_ratio=_env_float("TPU_TENANT_FLOOD_RATIO", 2.0),
            tenant_dwell_ticks=_env_int("TPU_TENANT_DWELL_TICKS", 3),
            tenant_slo_tenants=_env_list("TPU_TENANT_SLO"),
            tpu_read_mirror=_env_bool("TPU_READ_MIRROR", True),
            tpu_mirror_max_stale_ms=_env_int(
                "TPU_MIRROR_MAX_STALE_MS", 5000
            ),
            tpu_readers=_bounded(
                "TPU_READERS", _env_int("TPU_READERS", 4), 1, 64
            ),
            tpu_mirror_segment_bytes=_bounded(
                "TPU_MIRROR_SEGMENT_BYTES",
                _env_int("TPU_MIRROR_SEGMENT_BYTES", 0),
                64 << 10,
                1 << 30,
                allow_zero=True,
            ),
            tpu_reader_port_base=_bounded(
                "TPU_READER_PORT_BASE",
                _env_int("TPU_READER_PORT_BASE", 9512),
                1025,  # base-1 hosts the supervisor endpoint, keep it unprivileged
                65000,
            ),
            deadline_propagation_enabled=_env_bool("TPU_DEADLINES", True),
            tpu_resume_dir=resume_dir,
            tpu_checkpoint_dir=os.environ.get("TPU_CHECKPOINT_DIR")
            or (os.path.join(resume_dir, "snap") if resume_dir else None),
            tpu_wal_dir=os.environ.get("TPU_WAL_DIR")
            or (os.path.join(resume_dir, "wal") if resume_dir else None),
            tpu_wal_fsync=_env_bool("TPU_WAL_FSYNC", False),
            tpu_archive_dir=archive_dir,
            tpu_archive_max_bytes=_env_int(
                "TPU_ARCHIVE_MAX_BYTES", 2 << 30
            ),
            tpu_archive_segment_bytes=_env_int(
                "TPU_ARCHIVE_SEGMENT_BYTES", 64 << 20
            ),
            tpu_snapshot_interval_s=_env_float("TPU_SNAPSHOT_INTERVAL_S", 300.0),
            tpu_snapshot_keep=_env_int("TPU_SNAPSHOT_KEEP", 2),
            tpu_scrub_interval_s=_env_float("TPU_SCRUB_INTERVAL_S", 300.0),
            tpu_scrub_bytes_per_sec=_env_int(
                "TPU_SCRUB_BYTES_PER_S", 8 << 20
            ),
            tpu_sampling=_env_bool("TPU_SAMPLING", False),
            tpu_sampling_budget=_env_float("TPU_SAMPLING_BUDGET", 0.0),
            tpu_sampling_interval_s=_env_float("TPU_SAMPLING_INTERVAL_S", 5.0),
            tpu_sampling_min_rate=_env_int("TPU_SAMPLING_MIN_RATE", 256),
            tpu_sampling_tail_quantile=_env_float(
                "TPU_SAMPLING_TAIL_QUANTILE", 0.99
            ),
            tpu_sampling_rare_min=_env_int("TPU_SAMPLING_RARE_MIN", 4),
            tpu_agg=_env_agg(),
        )


# AggConfig fields sizable from the environment (TPU_MAX_SERVICES=256 etc.)
_AGG_ENV_FIELDS = (
    "max_services", "max_keys", "hll_precision", "digest_centroids",
    "digest_buffer", "ring_capacity", "link_buckets", "bucket_minutes",
    "hist_slices", "hist_slice_minutes",
    # time-disaggregated sketch tier (TPU_TIME_BUCKETS=0 disables)
    "time_buckets", "time_bucket_minutes", "time_digest_centroids",
)


def _env_agg() -> dict:
    out = {}
    for field in _AGG_ENV_FIELDS:
        raw = os.environ.get("TPU_" + field.upper())
        if raw:
            out[field] = int(raw)
    return out
