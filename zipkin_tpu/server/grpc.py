"""gRPC span collector: ``zipkin.proto3.SpanService/Report``.

Reference semantics: ``ZipkinGrpcCollector.java`` (SURVEY.md §2.4),
enabled by ``COLLECTOR_GRPC_ENABLED``. Like the reference — which ships
hand-rolled proto field writers instead of protoc codegen — this uses the
framework's own proto3 codec (zipkin_tpu/model/proto3.py) and registers a
generic method handler, so there is no generated stub to drift from the
wire format.

The request body IS a ``ListOfSpans`` (the same bytes the HTTP collector
accepts as application/x-protobuf); the response is an empty
``ReportResponse``.

Observability parity with the HTTP site (ISSUE 8): every Report records
the ``grpc_boundary`` obs stage (request bytes → collector hand-off), so
the fan-out tier's gRPC leg shows up on ``/statusz`` and the stage
histograms exactly like HTTP ingest does. Incoming B3 ids on the
invocation metadata (``x-b3-traceid``/``x-b3-spanid``, the lowercase
metadata forms of the B3 headers) are published to
``obs.selfspans.CURRENT_B3`` for the duration of the call — contextvars
survive ``asyncio.to_thread`` — so slow-dispatch self-spans triggered
while serving a gRPC report parent under the caller's trace, matching
the HTTP self-tracing middleware. ``x-b3-sampled: 0`` suppresses the
linkage per the B3 spec.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional

import grpc
import grpc.aio

from zipkin_tpu import obs
from zipkin_tpu.collector.core import Collector
from zipkin_tpu.model.codec import Encoding
from zipkin_tpu.obs import critpath
from zipkin_tpu.obs.selfspans import CURRENT_B3
from zipkin_tpu.runtime.tenant import (
    CURRENT_TENANT,
    TENANT_METADATA_KEY,
    normalize_tenant,
)

logger = logging.getLogger(__name__)

SERVICE = "zipkin.proto3.SpanService"
METHOD = f"/{SERVICE}/Report"


def _stamped_request(data: bytes):
    """Request deserializer that timestamps message receipt.

    grpc's C core assembles the request message (socket reads, HTTP/2
    reassembly, the ~5 MB body of a 64k-span ListOfSpans) BEFORE the
    Python handler runs, so a ``t0`` taken inside ``report()`` misses
    the read entirely — INGEST_r07 showed ``grpc_boundary`` at 0.16 µs
    vs ``http_boundary``'s 0.73 µs for identical proto3 work. The
    deserializer is the earliest Python hook after assembly: stamping
    here makes the stage span request read + decode like the HTTP
    site's (whose t0 precedes ``request.read()``)."""
    return time.perf_counter_ns(), data


class _SpanServiceHandler(grpc.GenericRpcHandler):
    def __init__(self, collector: Collector, deadlines: bool = True) -> None:
        self._collector = collector
        self._deadlines = deadlines

    def _retry_trailers(self, exc=None):
        """Backoff guidance for a RESOURCE_EXHAUSTED shed (ISSUE 13/18):
        the backoff delay as ``retry-delay`` trailing metadata (seconds,
        decimal) — the gRPC twin of the HTTP site's Retry-After header.
        When the shed carries a scope (tenant-budget vs global-ladder,
        ISSUE 18) the trailers also say WHICH control rejected the
        payload (``shed-scope``/``shed-tenant``) and the delay comes
        from that tenant's own deficit, not the global ladder."""
        ctl = getattr(self._collector, "overload", None)
        if ctl is None:
            return None
        delay_s = getattr(exc, "retry_after_s", None)
        scope = getattr(exc, "scope", None)
        tenant = getattr(exc, "tenant", None)
        if delay_s is None:
            delay_s = ctl.retry_after_s(tenant if scope == "tenant" else None)
        trailers = [
            ("retry-delay", f"{delay_s:.3f}s"),
            ("retry-delay-ms", str(int(delay_s * 1000.0))),
        ]
        if scope:
            trailers.append(("shed-scope", str(scope)))
        if tenant:
            trailers.append(("shed-tenant", str(tenant)))
        return tuple(trailers)

    def service(self, handler_call_details):
        if handler_call_details.method != METHOD:
            return None

        # zt-ingest-boundary: gRPC Report is a wire entrypoint — tenant
        # identity is extracted from invocation metadata here, before the
        # collector chokepoint runs admission
        async def report(request, context) -> bytes:
            t0_ns, data = request
            critpath.WIRE_T0_NS.set(t0_ns)
            # deadline propagation (ISSUE 13): the client's gRPC
            # deadline may already be spent (the message sat in HTTP/2
            # reassembly or the accept queue) — drop before the
            # collector dispatches work nobody awaits
            if self._deadlines:
                remaining = context.time_remaining()
                if remaining is not None and remaining <= 0:
                    ctl = getattr(self._collector, "overload", None)
                    if ctl is not None:
                        ctl.note_deadline_expired()
                    await context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "deadline expired before dispatch",
                    )
            md = dict(context.invocation_metadata() or ())
            tid, sid = md.get("x-b3-traceid"), md.get("x-b3-spanid")
            sampled = str(md.get("x-b3-sampled", "")).lower()
            token = None
            if tid and sid and sampled not in ("0", "false"):
                token = CURRENT_B3.set((tid, sid))
            # tenant admission identity (ISSUE 18): lowercase metadata
            # form of the HTTP X-Tenant-Id header; absent/hostile values
            # normalize to the default tenant, so legacy clients keep
            # flowing. contextvars survive asyncio.to_thread.
            ten_tok = CURRENT_TENANT.set(
                normalize_tenant(md.get(TENANT_METADATA_KEY))
            )
            try:
                # off the event loop: decode + device ingest block, and the
                # loop is shared with the HTTP site (same fix as app.py)
                await asyncio.to_thread(
                    self._collector.accept_spans_bytes, data, Encoding.PROTO3
                )
            except ValueError as e:
                await context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
            except Exception as e:
                # storage rejection -> retryable; IngestBackpressure (a
                # tenant-budget shed, the fan-out tier's bounded queues
                # full, or the global brownout ladder) lands here too,
                # the gRPC twin of the HTTP site's 429 — trailing
                # metadata carries backoff guidance scoped to whichever
                # control rejected the payload
                trailers = self._retry_trailers(e)
                if trailers is not None:
                    context.set_trailing_metadata(trailers)
                await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            finally:
                CURRENT_TENANT.reset(ten_tok)
                if token is not None:
                    CURRENT_B3.reset(token)
            obs.record(
                "grpc_boundary", (time.perf_counter_ns() - t0_ns) / 1e9
            )
            return b""  # empty ReportResponse

        return grpc.unary_unary_rpc_method_handler(
            report,
            request_deserializer=_stamped_request,  # (t_recv_ns, bytes)
            response_serializer=None,
        )


class GrpcCollectorServer:
    """Lifecycle wrapper: bind, serve, drain."""

    def __init__(self, collector: Collector, host: str = "0.0.0.0",
                 port: int = 9412, deadlines: bool = True):
        self._collector = collector
        self._address = f"{host}:{port}"
        self._server: Optional[grpc.aio.Server] = None
        self.port = port
        self._deadlines = deadlines

    async def start(self) -> "GrpcCollectorServer":
        # span batches are big by design (a 64k-span ListOfSpans is
        # ~5 MB); grpc's 4 MB default would RESOURCE_EXHAUSTED them
        server = grpc.aio.server(options=[
            ("grpc.max_receive_message_length", 64 << 20),
            ("grpc.max_send_message_length", 64 << 20),
        ])
        server.add_generic_rpc_handlers(
            (_SpanServiceHandler(self._collector, self._deadlines),)
        )
        self.port = server.add_insecure_port(self._address)
        await server.start()
        self._server = server
        logger.info("grpc collector listening on %s", self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
