"""Self-tracing: the server traces its own request handling into itself.

Reference semantics: ``SELF_TRACING_ENABLED`` wires Brave into the server
and stores its own spans (SURVEY.md §5 tracing row). Here: an aiohttp
middleware records one SERVER span per handled request — method/path/
status tags, error tag on 5xx — sampled by ``SELF_TRACING_SAMPLE_RATE``
and fed through the normal collector pipeline (so self-spans are subject
to the same sampling/metrics as any other span).

B3 propagation: incoming ``X-B3-TraceId``/``X-B3-SpanId`` headers join
the caller's trace the way Brave would; otherwise a fresh trace id is
minted. ``X-B3-Sampled`` is honored per the B3 spec: ``0``/``false``
suppresses the self-span regardless of the local rate (the caller's
no-sample decision propagates), ``1``/``true``/``d`` forces it.

While a sampled request is in flight, ``obs.selfspans.CURRENT_B3``
carries (trace id, self-span id) so over-budget pipeline stages emit
their slow-dispatch spans parented under this request's trace.
"""

from __future__ import annotations

import asyncio
import random
import time
from typing import Optional

from aiohttp import web

from zipkin_tpu.collector.core import Collector, CollectorSampler
from zipkin_tpu.model.span import Endpoint, Kind, Span
from zipkin_tpu.obs.selfspans import CURRENT_B3

SERVICE_NAME = "zipkin-server"


def _new_id() -> str:
    return f"{random.getrandbits(64) or 1:016x}"


def _b3_sampled(header: Optional[str]) -> Optional[bool]:
    """Decode an ``X-B3-Sampled`` header: None when absent/garbage."""
    if header is None:
        return None
    value = header.strip().lower()
    if value in ("0", "false"):
        return False
    if value in ("1", "true", "d"):  # "d" = debug, implies sampled
        return True
    return None


def self_tracing_middleware(collector: Collector, sample_rate: float = 1.0):
    sampler = CollectorSampler(sample_rate)
    endpoint = Endpoint.create(SERVICE_NAME)

    @web.middleware
    async def middleware(request: web.Request, handler):
        trace_id = request.headers.get("X-B3-TraceId")
        parent_id: Optional[str] = request.headers.get("X-B3-SpanId")
        if not trace_id:
            trace_id, parent_id = _new_id(), None
        forced = _b3_sampled(request.headers.get("X-B3-Sampled"))
        span_id = _new_id()
        token = None
        if forced is not False:
            # Slow pipeline stages observed under this request B3-link
            # their self-spans here (contextvars survive to_thread).
            token = CURRENT_B3.set((trace_id, span_id))
        start = time.time_ns() // 1000
        status = 500
        try:
            response = await handler(request)
            status = response.status
            return response
        except web.HTTPException as e:
            status = e.status
            raise
        finally:
            if token is not None:
                CURRENT_B3.reset(token)
            duration = max(time.time_ns() // 1000 - start, 1)
            try:
                span = Span.create(
                    trace_id=trace_id,
                    id=span_id,
                    parent_id=parent_id,
                    kind=Kind.SERVER,
                    name=f"{request.method.lower()} {request.path}",
                    timestamp=start,
                    duration=duration,
                    local_endpoint=endpoint,
                    tags={
                        "http.method": request.method,
                        "http.path": request.path,
                        "http.status_code": str(status),
                        **({"error": str(status)} if status >= 500 else {}),
                    },
                )
                if forced is False:
                    pass  # caller said no-sample: honor it (B3 spec)
                elif forced is True or sampler.test(span):
                    # fire-and-forget off the event loop: storing a span
                    # may hit the device and must not stall serving
                    asyncio.get_running_loop().run_in_executor(
                        None, collector.accept, [span]
                    )
            except Exception:  # self-tracing must never break serving
                pass

    return middleware
