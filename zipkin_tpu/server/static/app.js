/* zipkin-tpu UI — hash-routed views (Discover, Trace, Dependencies, TPU
 * sketches) over the public JSON API only. Dependency-free by
 * construction: the box that serves it cannot fetch npm bundles.
 *
 * Security discipline (span fields are attacker-controlled — anyone can
 * POST to the collector): every string interpolated into markup goes
 * through esc(); SVG text uses textContent; trace ids are validated as
 * hex before use in URLs; event handlers are bound with addEventListener
 * + dataset indices, never inline JS built from payload strings; maps
 * (not plain objects) key anything payload-named, so "__proto__" cannot
 * poison lookups.
 */
'use strict';

const $ = q => document.querySelector(q);
const get = async p => {
  const r = await fetch(p);
  if (!r.ok) throw new Error(p.split('?')[0] + ': HTTP ' + r.status);
  return r.json();
};
const esc = s => String(s ?? '').replace(/[&<>"'`]/g, c => '&#' + c.charCodeAt(0) + ';');
const hexOnly = s => /^[0-9a-f]{1,32}$/.test(s) ? s : '';

/* µs → human units. Keeps raw µs under ~10ms (the range Lens shows raw). */
function fmtDur(us) {
  if (us == null || isNaN(us)) return '';
  if (us < 1000) return us + 'µs';
  if (us < 1e6) return (us / 1000).toFixed(us < 1e4 ? 2 : 1) + 'ms';
  return (us / 1e6).toFixed(2) + 's';
}

/* Deterministic service color: fnv-ish hash → hue. Same palette rules
 * everywhere (bars, chips, graph nodes, minimap) so a service is
 * recognizable across views. */
const _hueCache = new Map();
function svcHue(name) {
  if (_hueCache.has(name)) return _hueCache.get(name);
  let h = 2166136261;
  for (let i = 0; i < name.length; i++) { h ^= name.charCodeAt(i); h = Math.imul(h, 16777619); }
  const hue = ((h >>> 0) * 137) % 360;
  _hueCache.set(name, hue);
  return hue;
}
const svcColor = name => `hsl(${svcHue(name)},52%,44%)`;
const svcColorSoft = name => `hsl(${svcHue(name)},52%,62%)`;

/* ---------------------------------------------------------------- router */

const VIEWS = new Map();   // path prefix -> render(args, params)

/* Navigation generation: bumped on every route(). Async view code must
 * bail (`if (stale(gen)) return`) after each await before touching
 * #view, or a slow in-flight fetch would overwrite the view the user
 * navigated to meanwhile. */
let _gen = 0;
const stale = g => g !== _gen;

function route() {
  const h = (location.hash.slice(1) || '/');
  const [path, qs] = h.split('?');
  const params = new URLSearchParams(qs || '');
  const parts = path.replace(/^\/+/, '').split('/');
  const name = parts[0] || 'discover';
  const view = VIEWS.get(name) || VIEWS.get('discover');
  document.querySelectorAll('header a[data-nav]').forEach(a => {
    a.classList.toggle('active', a.dataset.nav === name);
  });
  closePanel();
  const gen = ++_gen;
  view(parts.slice(1), params, gen).catch(e => {
    if (stale(gen)) return;
    $('#view').innerHTML = `<section><p class="err">${esc(e.message)}</p></section>`;
  });
}

function nav(hash) { location.hash = hash; }

/* ------------------------------------------------------------ boot/header */

async function boot() {
  try {
    const i = await get('/info');
    $('#info').textContent = 'v' + i.zipkin.version + ' · ' + i.zipkin.flavor;
  } catch (e) { /* header version is cosmetic */ }
  window.addEventListener('hashchange', route);
  route();
}

/* ------------------------------------------------------------- discover */

let _services = null;
async function serviceList() {
  if (_services) return _services;
  try { _services = await get('/api/v2/services'); } catch (e) { _services = []; }
  return _services;
}

let _tagKeys = null;
async function tagKeyList() {
  if (_tagKeys) return _tagKeys;
  try {
    const keys = await get('/api/v2/autocompleteKeys');
    _tagKeys = Array.isArray(keys) ? keys : [];
  } catch (e) { _tagKeys = []; } // endpoint disabled: plain input
  return _tagKeys;
}

VIEWS.set('discover', async (args, params, gen) => {
  const services = await serviceList();
  if (stale(gen)) return;
  const el = $('#view');
  el.innerHTML = `
  <section><h2>Find traces</h2>
   <div style="display:flex;gap:6px;flex-wrap:wrap;align-items:center">
    <select id="svc"><option value="">all services</option></select>
    <select id="spanname"><option value="">all spans</option></select>
    <input id="annq" list="tagkeys" placeholder="annotationQuery: error and http.method=GET" style="width:22em">
    <datalist id="tagkeys"></datalist>
    <input id="mindur" type="number" placeholder="min µs" style="width:6.5em">
    <input id="maxdur" type="number" placeholder="max µs" style="width:6.5em">
    <select id="lookback">
     <option value="3600000">last hour</option>
     <option value="86400000">last day</option>
     <option value="604800000" selected>last 7 days</option>
    </select>
    <input id="limit" type="number" value="10" style="width:4.5em" title="limit">
    <select id="sort">
     <option value="newest">newest first</option>
     <option value="longest">longest first</option>
     <option value="spans">most spans</option>
    </select>
    <button id="gosearch" class="primary">search</button>
    <span style="margin-left:10px">trace id:
     <input id="tid" placeholder="hex trace id" style="width:17em">
     <button id="gotrace">open</button></span>
    <label style="margin-left:10px" title="view a span-list JSON file without storing it">
     local JSON: <input id="tracefile" type="file" accept=".json,application/json"></label>
   </div>
   <div id="traces" style="margin-top:10px"></div>
  </section>`;
  const svcSel = $('#svc');
  for (const n of services) {
    const o = document.createElement('option');
    o.value = o.textContent = n;
    svcSel.append(o);
  }
  // restore form state from the hash query so searches are shareable
  for (const [id, key] of [['svc', 'serviceName'], ['spanname', 'spanName'],
    ['annq', 'annotationQuery'], ['mindur', 'minDuration'],
    ['maxdur', 'maxDuration'], ['lookback', 'lookback'],
    ['limit', 'limit'], ['sort', 'sort']]) {
    if (params.has(key)) $('#' + id).value = params.get(key);
  }
  svcSel.addEventListener('change', loadNames);
  // autocomplete tag keys (the Lens discover suggestions) — cached per
  // session like serviceList(); best-effort
  tagKeyList().then(keys => {
    if (stale(gen)) return;
    const dl = $('#tagkeys');
    if (!dl) return;
    for (const k of keys) {
      const o = document.createElement('option');
      o.value = String(k);
      dl.append(o);
    }
  });
  $('#gosearch').addEventListener('click', () => {
    const target = '/?' + discoverQuery().toString();
    // same hash fires no hashchange — run the search directly so a
    // repeat click still picks up newly ingested traces (endTs=now is
    // applied inside findTraces)
    if (location.hash === '#' + target) findTraces();
    else nav(target);
  });
  $('#gotrace').addEventListener('click', () => {
    const id = hexOnly($('#tid').value.trim().toLowerCase());
    if (!id) { $('#traces').innerHTML = '<p class="err">not a hex trace id</p>'; return; }
    nav('/trace/' + id);
  });
  // the Lens "view my own JSON" path: render a span-list file in the
  // waterfall without ingesting it (same escaping rules apply — the
  // file is as untrusted as a POSTed payload)
  $('#tracefile').addEventListener('change', async ev => {
    const f = ev.target.files[0];
    if (!f) return;
    try {
      const spans = JSON.parse(await f.text());
      if (!Array.isArray(spans) || !spans.length) throw new Error('expected a non-empty span array');
      // element-level check: a [null] or [{}] entry would otherwise
      // blow up later inside treeOrder with a raw TypeError
      for (const s of spans) {
        if (!s || typeof s !== 'object' || typeof s.id !== 'string') {
          throw new Error('every span needs at least an "id" string');
        }
      }
      _localTrace = spans;
      nav('/trace/local');
    } catch (e) {
      $('#traces').innerHTML = `<p class="err">cannot load trace JSON: ${esc(e.message)}</p>`;
    }
  });
  if (params.has('serviceName')) await loadNames(params.get('spanName'));
  if ([...params.keys()].length) await findTraces();
});

function discoverQuery() {
  const q = new URLSearchParams();
  const setIf = (key, v) => { if (v) q.set(key, v); };
  setIf('serviceName', $('#svc').value);
  setIf('spanName', $('#spanname').value);
  setIf('annotationQuery', $('#annq').value.trim());
  setIf('minDuration', $('#mindur').value);
  setIf('maxDuration', $('#maxdur').value);
  q.set('lookback', $('#lookback').value || 7 * 864e5);
  q.set('limit', $('#limit').value || 10);
  setIf('sort', $('#sort').value !== 'newest' ? $('#sort').value : '');
  return q;
}

async function loadNames(selected) {
  const svc = $('#svc').value, sel = $('#spanname');
  sel.innerHTML = '<option value="">all spans</option>';
  if (!svc) return;
  try {
    const names = await get('/api/v2/spans?serviceName=' + encodeURIComponent(svc));
    for (const n of names) {
      const o = document.createElement('option');
      o.value = o.textContent = n;
      sel.append(o);
    }
    if (typeof selected === 'string') sel.value = selected;
  } catch (e) { /* names dropdown stays empty */ }
}

async function findTraces() {
  const gen = _gen;
  const elq = $('#traces');
  const q = discoverQuery();
  const sort = q.get('sort') || 'newest';
  q.delete('sort');
  q.set('endTs', Date.now());
  elq.innerHTML = '<p class="muted">searching…</p>';
  let traces;
  try { traces = await get('/api/v2/traces?' + q); }
  catch (e) {
    if (stale(gen)) return;
    elq.innerHTML = `<p class="err">search failed: ${esc(e.message)} (check the filter values)</p>`;
    return;
  }
  if (stale(gen)) return;
  // an empty trace array has no root span — tr.reduce with no initial
  // value throws on it and would blank the whole results table
  traces = traces.filter(tr => tr.length);
  if (!traces.length) { elq.innerHTML = '<p class="muted">no traces matched</p>'; return; }

  const rows = traces.map(tr => {
    // reduce, not Math.min(...spread): a >65k-span trace would blow the
    // JS argument-count limit (same rule as depGraph's maxC)
    const root = tr.reduce((a, b) => (a.timestamp || 1e18) < (b.timestamp || 1e18) ? a : b);
    const t0 = tr.reduce((m, s) => Math.min(m, s.timestamp || 1e18), 1e18);
    const t1 = tr.reduce((m, s) => Math.max(m, (s.timestamp || t0) + (s.duration || 0)), 0);
    // per-service share of span time, for the segmented duration bar
    const share = new Map();
    for (const s of tr) {
      const svc = (s.localEndpoint || {}).serviceName;
      if (svc && s.duration) share.set(svc, (share.get(svc) || 0) + s.duration);
    }
    return {
      spans: tr, root, dur: t1 - t0 || root.duration || 0,
      id: hexOnly(root.traceId),
      err: tr.some(s => s.tags && s.tags.error !== undefined),
      share: [...share.entries()].sort((a, b) => b[1] - a[1]),
    };
  });
  if (sort === 'longest') rows.sort((a, b) => b.dur - a.dur);
  else if (sort === 'spans') rows.sort((a, b) => b.spans.length - a.spans.length);
  else rows.sort((a, b) => (b.root.timestamp || 0) - (a.root.timestamp || 0));
  const maxDur = rows.reduce((m, r) => Math.max(m, r.dur), 1);

  let h = `<table><tr><th>start</th><th>trace</th><th>duration</th>
    <th style="width:28%">relative · by service</th><th>spans</th><th>services</th></tr>`;
  rows.forEach((r, i) => {
    const when = r.root.timestamp
      ? new Date(r.root.timestamp / 1000).toISOString().slice(0, 19).replace('T', ' ') : '';
    const segs = [];
    let off = 0;
    const total = r.share.reduce((a, [, d]) => a + d, 0) || 1;
    const w = 100 * r.dur / maxDur;
    for (const [svc, d] of r.share.slice(0, 6)) {
      const sw = w * d / total;
      segs.push(`<div style="left:${off}%;width:${Math.max(sw, 0.4)}%;background:${svcColor(svc)}"
        title="${esc(svc)}: ${esc(fmtDur(d))}"></div>`);
      off += sw;
    }
    if (!segs.length) segs.push(`<div style="left:0;width:${Math.max(w, 0.4)}%;background:#9fa8da"></div>`);
    const chips = r.share.slice(0, 4).map(([svc, d]) =>
      `<span class="chip" style="background:${svcColor(svc)}">${esc(svc)}<span class="n">${esc(fmtDur(d))}</span></span>`);
    h += `<tr class="trow" data-id="${r.id}"><td>${esc(when)}</td>
      <td>${esc(r.id.slice(0, 16))}${r.err ? '<span class="badge-err">error</span>' : ''}</td>
      <td>${esc(fmtDur(r.dur))}</td>
      <td><div class="durbar">${segs.join('')}</div></td>
      <td>${r.spans.length}</td>
      <td>${chips.join('')}${r.share.length > 4 ? '<span class="muted"> +' + (r.share.length - 4) + '</span>' : ''}</td></tr>`;
  });
  elq.innerHTML = h + '</table>';
  elq.querySelectorAll('tr.trow').forEach(row =>
    row.addEventListener('click', () => nav('/trace/' + row.dataset.id)));
}

/* ---------------------------------------------------------------- trace */

let curSpans = [];          // tree-ordered spans of the open trace
let curTree = [];           // [[span, depth], ...]
let collapsed = new Set();  // indices whose subtree is folded
let curT0 = 0, curTotal = 1;  // trace time origin/extent for renderRows
let pctCtx = new Map();     // "service|span" -> {p50, p99}
let _localTrace = null;     // spans loaded from a local JSON file

async function loadPctCtx() {
  if (pctCtx.size) return;
  try {
    const rows = await get('/api/v2/tpu/percentiles?q=0.5,0.99');
    for (const x of rows) pctCtx.set(x.serviceName + '|' + x.spanName,
      { p50: x.quantiles['0.5'], p99: x.quantiles['0.99'] });
  } catch (e) { /* TPU sketches not enabled: waterfall renders without context */ }
}

function treeOrder(spans) {
  // Lens-style waterfall order: DFS over the span tree (parentId edges;
  // a shared SERVER span nests under its same-id client half), children
  // by timestamp; orphans (missing parents) surface as roots.
  // Returns [[span, depth], ...]. Cycle-safe via the visited set.
  const byId = new Map();
  for (const s of spans) {
    const k = s.id;
    if (!byId.has(k)) byId.set(k, []);
    byId.get(k).push(s);
  }
  const parentOf = s => {
    if (s.shared) {  // server half: parent is the client half (same id)
      const mates = (byId.get(s.id) || []).filter(m => m !== s && !m.shared);
      if (mates.length) return mates[0];
    }
    if (s.parentId && byId.has(s.parentId)) {
      // prefer the SHARED rendition (the server half is the closer tree
      // node — SpanNode's index preference), so server-created children
      // nest under the server span, not beside it
      const c = byId.get(s.parentId);
      return c.find(m => m.shared) || c[0];
    }
    return null;
  };
  const kids = new Map(), roots = [];
  for (const s of spans) {
    const p = parentOf(s);
    if (p) { if (!kids.has(p)) kids.set(p, []); kids.get(p).push(s); }
    else roots.push(s);
  }
  const ts = s => s.timestamp || 1e18;
  roots.sort((a, b) => ts(a) - ts(b));
  const out = [], seen = new Set();
  const walk = (s, d) => {
    if (seen.has(s)) return;
    seen.add(s);
    out.push([s, d]);
    const c = (kids.get(s) || []).sort((a, b) => ts(a) - ts(b));
    for (const k of c) walk(k, d + 1);
  };
  for (const r of roots) walk(r, 0);
  for (const s of spans) if (!seen.has(s)) out.push([s, 0]); // cycle leftovers
  return out;
}

/* #spans whose subtree a row at index i covers: following rows with
 * depth > depth[i], contiguously. */
function subtreeEnd(i) {
  const d = curTree[i][1];
  let j = i + 1;
  while (j < curTree.length && curTree[j][1] > d) j++;
  return j;
}

VIEWS.set('trace', async (args, params, gen) => {
  let id, spans;
  if (args[0] === 'local' && _localTrace) {
    // a file loaded on the Discover page; 'local' never collides with
    // hexOnly ids and a cold deep-link to #/trace/local falls through
    // to the hex branch's error
    id = 'local';
    spans = _localTrace;
    await loadPctCtx();
  } else {
    id = hexOnly((args[0] || '').toLowerCase());
    if (!id) throw new Error('not a hex trace id');
    [spans] = await Promise.all([get('/api/v2/trace/' + id), loadPctCtx()]);
  }
  if (stale(gen)) return;
  curTree = treeOrder(spans);
  curSpans = curTree.map(([s]) => s);
  collapsed = new Set();
  const svcs = [...new Set(spans.map(s => (s.localEndpoint || {}).serviceName).filter(Boolean))];
  // reduce, not Math.min(...spread): a >65k-span trace would blow the
  // JS argument-count limit
  const t0 = spans.reduce((m, s) => Math.min(m, s.timestamp || 1e18), 1e18);
  const total = spans.reduce((m, s) => Math.max(m, (s.timestamp || t0) + (s.duration || 0)), 0) - t0 || 1;
  const depth = curTree.reduce((m, [, d]) => Math.max(m, d), 0);
  const errs = spans.filter(s => s.tags && s.tags.error !== undefined).length;

  const el = $('#view');
  el.innerHTML = `
  <section>
   <h2>trace ${esc(id)}
    <span class="muted">${spans.length} spans · ${svcs.length} services · depth ${depth + 1}
     · ${esc(fmtDur(total))}${errs ? ` · <span class="err">${errs} error spans</span>` : ''}</span>
    <span style="float:right">
     <button id="expandall">expand all</button>
     <button id="dljson">download JSON</button>
     <a href="#/" style="margin-left:8px">← back to search</a></span>
   </h2>
   <div id="legend" style="margin:6px 0"></div>
   <svg id="minimap" height="54"></svg>
   <table class="wf"><tr><th class="names">service · span</th>
    <th class="tl"><div id="ruler"></div></th>
    <th style="width:7em">duration</th><th style="width:5.5em">vs p99</th></tr>
    <tbody id="wfrows"></tbody></table>
  </section>`;

  // legend: service chips with span counts, colored like the bars
  const counts = new Map();
  for (const s of spans) {
    const svc = (s.localEndpoint || {}).serviceName;
    if (svc) counts.set(svc, (counts.get(svc) || 0) + 1);
  }
  $('#legend').innerHTML = [...counts.entries()].sort((a, b) => b[1] - a[1]).map(([svc, n]) =>
    `<span class="chip" style="background:${svcColor(svc)}">${esc(svc)}<span class="n">×${n}</span></span>`).join('');

  // ruler: 5 ticks, µs/ms adaptive
  $('#ruler').innerHTML = [0, 0.25, 0.5, 0.75, 1].map(f =>
    `<span style="left:${f * 100}%">${esc(fmtDur(Math.round(total * f)))}</span>`).join('');

  $('#dljson').addEventListener('click', () => {
    const blob = new Blob([JSON.stringify(spans, null, 2)], { type: 'application/json' });
    const a = document.createElement('a');
    a.href = URL.createObjectURL(blob);
    a.download = 'trace-' + id + '.json';
    a.click();
    URL.revokeObjectURL(a.href);
  });
  $('#expandall').addEventListener('click', () => { collapsed.clear(); renderRows(); });

  curT0 = t0;
  curTotal = total;
  drawMinimap(t0, total);
  renderRows();
});

function drawMinimap(t0, total) {
  const svg = $('#minimap');
  const NS = 'http://www.w3.org/2000/svg';
  svg.innerHTML = '';
  const W = 1000, H = 54;
  svg.setAttribute('viewBox', `0 0 ${W} ${H}`);
  svg.setAttribute('preserveAspectRatio', 'none');
  const n = curTree.length;
  const rh = Math.max(Math.min(H / n, 4), 0.8);
  curTree.forEach(([s], i) => {
    const x = W * ((s.timestamp || t0) - t0) / total;
    const w = Math.max(W * (s.duration || 0) / total, 1.5);
    const r = document.createElementNS(NS, 'rect');
    const err = s.tags && s.tags.error !== undefined;
    const svc = (s.localEndpoint || {}).serviceName || '';
    r.setAttribute('x', x); r.setAttribute('y', Math.min(i * rh, H - rh));
    r.setAttribute('width', w); r.setAttribute('height', Math.max(rh - 0.4, 0.6));
    r.setAttribute('fill', err ? '#b71c1c' : svcColorSoft(svc));
    svg.append(r);
  });
  svg.addEventListener('click', ev => {
    // clientY relative to the svg box (offsetY can be rect-relative
    // when the click lands on a child), then into viewBox units and
    // divided by the DRAWN row height — rh is clamped, so frac*n would
    // mis-target any trace where rh != H/n
    const box = svg.getBoundingClientRect();
    const vbY = (ev.clientY - box.top) / (box.height || 1) * H;
    let idx = Math.max(0, Math.min(Math.floor(vbY / rh), n - 1));
    // the exact index may sit inside a collapsed subtree (its row is
    // not rendered) — walk up to the nearest rendered ancestor row
    let row = null;
    while (idx >= 0 && !(row = document.querySelector(`tr.srow[data-idx="${idx}"]`))) idx--;
    if (row) selectRow(row, 'center');
  });
}

function renderRows() {
  const t0 = curT0, total = curTotal;
  const tbody = $('#wfrows');
  _selRow = null;
  let h = '';
  let skipUntil = -1;
  curTree.forEach(([s, depthv], i) => {
    if (i < skipUntil) return;
    const end = subtreeEnd(i);
    const nkids = end - i - 1;
    const folded = collapsed.has(i);
    if (folded) skipUntil = end;
    const off = 100 * ((s.timestamp || t0) - t0) / total;
    const w = Math.max(100 * (s.duration || 0) / total, 0.4);
    const err = s.tags && s.tags.error !== undefined;
    const svc = (s.localEndpoint || {}).serviceName || '';
    const key = svc + '|' + (s.name || '');
    const ctx = pctCtx.get(key);
    // duration-percentile context from the device sketches (the Lens
    // "how slow is this span vs its peers" panel)
    let vs = '';
    if (ctx && s.duration) {
      const r = s.duration / ctx.p99;
      vs = r >= 1 ? `<span class="slow">${r.toFixed(1)}x p99</span>`
        : s.duration >= ctx.p50 ? '&gt;p50' : '&lt;p50';
    }
    const pad = Math.min(depthv, 14) * 13;
    const caret = nkids
      ? `<span class="caret" data-fold="${i}">${folded ? '▸' : '▾'}</span>`
      : '<span class="caret"></span>';
    const grid = [25, 50, 75].map(p => `<div class="grid" style="left:${p}%"></div>`).join('');
    h += `<tr class="srow ${err ? 'err' : ''}" data-idx="${i}">
      <td class="names" style="padding-left:${6 + pad}px">${caret}
        <span class="svc-dot" style="background:${svcColor(svc)}"></span>${esc(svc)}
        <span class="muted">· ${esc(s.name || '')} ${esc(s.kind || '')}${s.shared ? ' shared' : ''}</span>
        ${folded ? `<span class="hiddenkids">+${nkids} hidden</span>` : ''}</td>
      <td class="tl">${grid}<div class="bar ${err ? 'err' : ''}"
        style="margin-left:${off}%;width:${w}%;background:${svcColor(svc)}"></div></td>
      <td>${esc(fmtDur(s.duration))}</td><td>${vs}</td></tr>`;
  });
  tbody.innerHTML = h;
  tbody.querySelectorAll('.caret[data-fold]').forEach(c =>
    c.addEventListener('click', ev => {
      ev.stopPropagation();
      const i = +c.dataset.fold;
      collapsed.has(i) ? collapsed.delete(i) : collapsed.add(i);
      renderRows();
    }));
  tbody.querySelectorAll('tr.srow').forEach(row =>
    row.addEventListener('click', () => selectRow(row)));
}

/* Single selection anchor for click, minimap and keyboard paths —
 * tracked so selecting is O(1), not a sweep over (possibly 65k) rows. */
let _selRow = null;
function selectRow(row, scroll) {
  if (_selRow && _selRow !== row) _selRow.classList.remove('sel');
  _selRow = row;
  row.classList.add('sel');
  if (scroll) row.scrollIntoView({ block: scroll });
  spanDetail(+row.dataset.idx);
}

/* Keyboard navigation on the waterfall: ↑/↓ move the selection over the
 * RENDERED rows, ←/→ fold/unfold the selected subtree, Escape closes
 * the span panel. Inactive while typing in a form control. */
document.addEventListener('keydown', ev => {
  const tag = (ev.target.tagName || '').toLowerCase();
  if (tag === 'input' || tag === 'select' || tag === 'textarea') return;
  // Escape works on EVERY view with a span panel (the Dependencies view
  // opens one too), so it is handled before the trace-route gate
  if (ev.key === 'Escape') { closePanel(); return; }
  if (!location.hash.startsWith('#/trace/')) return;
  if (ev.key === 'ArrowDown' || ev.key === 'ArrowUp') {
    ev.preventDefault();
    const anchor = _selRow && _selRow.isConnected ? _selRow : null;
    const next = anchor
      ? (ev.key === 'ArrowDown'
        ? anchor.nextElementSibling : anchor.previousElementSibling)
      : document.querySelector('tr.srow');
    if (next && next.classList.contains('srow')) selectRow(next, 'nearest');
  } else if ((ev.key === 'ArrowLeft' || ev.key === 'ArrowRight')
      && _selRow && _selRow.isConnected) {
    const i = +_selRow.dataset.idx;
    if (subtreeEnd(i) - i - 1 === 0) return;  // leaf: nothing to fold
    // no-op fold/unfold must not rebuild a (possibly 65k-row) waterfall
    if ((ev.key === 'ArrowLeft') === collapsed.has(i)) return;
    ev.preventDefault();
    if (ev.key === 'ArrowLeft') collapsed.add(i);
    else collapsed.delete(i);
    renderRows();
    const again = document.querySelector(`tr.srow[data-idx="${i}"]`);
    if (again) selectRow(again);
  }
});

function spanDetail(i) {
  const s = curSpans[i];
  if (!s) return;
  const row = (k, v) => v === undefined || v === '' ? '' : `<tr><th>${esc(k)}</th><td>${esc(v)}</td></tr>`;
  const ep = e => e ? [e.serviceName, e.ipv4 || e.ipv6, e.port].filter(Boolean).join(' ') : '';
  let h = `<button class="close" id="panelclose">×</button>
    <h3>${esc(s.name || '(unnamed)')} <span class="muted">${esc(s.kind || '')}</span></h3><table>`;
  h += row('traceId', s.traceId) + row('spanId', s.id) + row('parentId', s.parentId)
    + row('shared', s.shared ? 'true' : '') + row('timestamp µs', s.timestamp)
    + row('duration', fmtDur(s.duration))
    + row('local', ep(s.localEndpoint)) + row('remote', ep(s.remoteEndpoint));
  const ctx = pctCtx.get(((s.localEndpoint || {}).serviceName || '') + '|' + (s.name || ''));
  if (ctx) h += row('peer p50', fmtDur(Math.round(ctx.p50))) + row('peer p99', fmtDur(Math.round(ctx.p99)));
  h += '</table>';
  if (s.annotations && s.annotations.length) {
    h += '<h3>annotations</h3><table>';
    for (const a of s.annotations) h += row(a.timestamp, a.value);
    h += '</table>';
  }
  const tags = s.tags || {};
  if (Object.keys(tags).length) {
    h += '<h3>tags</h3><table>';
    for (const k of Object.keys(tags).sort())
      h += `<tr><th class="${k === 'error' ? 'err' : ''}">${esc(k)}</th><td>${esc(tags[k])}</td></tr>`;
    h += '</table>';
  }
  openPanel(h);
}

function openPanel(html) {
  const p = $('#spanpanel');
  p.innerHTML = html;
  p.style.display = 'block';
  const c = $('#panelclose');
  if (c) c.addEventListener('click', closePanel);
}
function closePanel() {
  const p = $('#spanpanel');
  if (p) { p.style.display = 'none'; p.innerHTML = ''; }
}

/* ---------------------------------------------------------- dependencies */

let curLinks = [];

VIEWS.set('dependencies', async (args, params) => {
  const lookback = params.get('lookback') || 7 * 864e5;
  const el = $('#view');
  el.innerHTML = `
  <section><h2>Dependencies
    <span class="muted">service call graph from <code>/api/v2/dependencies</code> —
    click a service for its callers/callees</span></h2>
   <select id="deplb">
    <option value="3600000">last hour</option>
    <option value="86400000">last day</option>
    <option value="604800000">last 7 days</option>
    <option value="2592000000">last 30 days</option>
   </select>
   <button id="deprefresh" class="primary">refresh</button>
   <svg id="depgraph" width="100%" height="0" viewBox="0 0 800 500"></svg>
   <table id="deptab"></table>
  </section>`;
  $('#deplb').value = String(lookback);
  $('#deprefresh').addEventListener('click', () => {
    const target = '/dependencies?lookback=' + $('#deplb').value;
    // same hash fires no hashchange — refresh must refetch regardless
    if (location.hash === '#' + target) deps(+$('#deplb').value);
    else nav(target);
  });
  await deps(+lookback);
});

async function deps(lookback) {
  const gen = _gen;
  let links;
  try {
    links = await get('/api/v2/dependencies?endTs=' + Date.now() + '&lookback=' + lookback);
  } catch (e) {
    // refresh clicks call deps() directly — a failed refetch must show
    // inline, not vanish as an unhandled rejection behind stale data
    if (stale(gen)) return;
    $('#deptab').innerHTML = `<tr><td class="err">dependencies fetch failed: ${esc(e.message)}</td></tr>`;
    $('#depgraph').setAttribute('height', '0');
    return;
  }
  if (stale(gen)) return;
  curLinks = links;
  const t = $('#deptab');
  let h = '<tr><th>parent</th><th>child</th><th>calls</th><th>errors</th><th>error rate</th></tr>';
  const sorted = [...links].sort((a, b) => (b.callCount || 0) - (a.callCount || 0));
  sorted.forEach(l => {
    const rate = l.callCount ? (100 * (l.errorCount || 0) / l.callCount) : 0;
    h += `<tr class="trow" data-svc="${esc(l.parent)}">
      <td><span class="svc-dot" style="background:${svcColor(l.parent)}"></span>${esc(l.parent)}</td>
      <td><span class="svc-dot" style="background:${svcColor(l.child)}"></span>${esc(l.child)}</td>
      <td>${esc(l.callCount)}</td>
      <td class="${l.errorCount ? 'err' : ''}">${esc(l.errorCount || 0)}</td>
      <td class="${rate > 1 ? 'err' : 'muted'}">${rate.toFixed(rate && rate < 10 ? 1 : 0)}%</td></tr>`;
  });
  t.innerHTML = h;
  t.querySelectorAll('tr.trow').forEach(row =>
    row.addEventListener('click', () => serviceDetail(row.dataset.svc)));
  depGraph(links);
}

function serviceDetail(name) {
  // callers/callees panel for one service, from the loaded link set
  const inbound = curLinks.filter(l => l.child === name);
  const outbound = curLinks.filter(l => l.parent === name);
  const sum = ls => ls.reduce((a, l) => a + (l.callCount || 0), 0);
  const errs = ls => ls.reduce((a, l) => a + (l.errorCount || 0), 0);
  const table = (ls, key) => ls.length
    ? '<table>' + ls.sort((a, b) => b.callCount - a.callCount).map(l =>
      `<tr><th><span class="svc-dot" style="background:${svcColor(l[key])}"></span>${esc(l[key])}</th>
       <td>${esc(l.callCount)} calls</td>
       <td class="${l.errorCount ? 'err' : 'muted'}">${esc(l.errorCount || 0)} errors</td></tr>`).join('') + '</table>'
    : '<p class="muted">none</p>';
  openPanel(`<button class="close" id="panelclose">×</button>
    <h3><span class="svc-dot" style="background:${svcColor(name)}"></span>${esc(name)}</h3>
    <table>
     <tr><th>calls in</th><td>${sum(inbound)} (${errs(inbound)} errors)</td></tr>
     <tr><th>calls out</th><td>${sum(outbound)} (${errs(outbound)} errors)</td></tr>
    </table>
    <h3>callers (${inbound.length})</h3>${table(inbound, 'parent')}
    <h3>callees (${outbound.length})</h3>${table(outbound, 'child')}
    <p><a href="#/?serviceName=${encodeURIComponent(name)}&lookback=604800000&limit=10">find traces →</a></p>`);
}

function depGraph(links) {
  // service graph (the Lens dependencies view): nodes on a circle,
  // directed edges with width ~ log(calls), red when errors flow.
  // Built with createElementNS + textContent only — span/service names
  // are attacker-controlled and never touch innerHTML here.
  const svg = $('#depgraph');
  const NS = 'http://www.w3.org/2000/svg';
  svg.innerHTML = '';
  // rank services by call volume so a >48-service graph keeps the heavy
  // hitters, and SAY what was dropped (a silently truncated graph reads
  // as "those call paths do not exist"). Maps, not plain objects:
  // service names are attacker-controlled and "__proto__"/"constructor"
  // would corrupt object-keyed lookups.
  const vol = new Map();
  for (const l of links) {
    vol.set(l.parent, (vol.get(l.parent) || 0) + (l.callCount || 0));
    vol.set(l.child, (vol.get(l.child) || 0) + (l.callCount || 0));
  }
  const all = [...vol.keys()].sort((a, b) => vol.get(b) - vol.get(a));
  const names = all.slice(0, 48);
  if (!names.length) { svg.setAttribute('height', '0'); return; }
  svg.setAttribute('height', '500');
  const cx = 400, cy = 250, R = Math.min(200, 60 + names.length * 8);
  const pos = new Map();
  names.forEach((n, i) => {
    const a = 2 * Math.PI * i / names.length - Math.PI / 2;
    pos.set(n, [cx + R * Math.cos(a), cy + R * Math.sin(a)]);
  });
  const el = (k, at) => {
    const e = document.createElementNS(NS, k);
    for (const [a, v] of Object.entries(at)) e.setAttribute(a, v);
    return e;
  };
  // reduce, not Math.max(...spread): a 100k-link response would blow
  // the JS argument-count limit
  const maxC = links.reduce((m, l) => Math.max(m, l.callCount || 1), 1);
  for (const l of links) {
    const p = pos.get(l.parent), c = pos.get(l.child);
    if (!p || !c) continue;
    const w = 0.8 + 3 * Math.log(1 + (l.callCount || 1)) / Math.log(1 + maxC);
    // curve through a point pulled toward the center so opposite-
    // direction edges between the same pair stay distinguishable
    const mx = (p[0] + c[0]) / 2 + (cy - (p[1] + c[1]) / 2) * 0.25,
      my = (p[1] + c[1]) / 2 + ((p[0] + c[0]) / 2 - cx) * 0.25;
    const path = el('path', {
      d: `M${p[0]},${p[1]} Q${mx},${my} ${c[0]},${c[1]}`,
      fill: 'none', stroke: l.errorCount ? '#b71c1c' : '#7986cb',
      'stroke-width': w, opacity: 0.75,
    });
    const tip = document.createElementNS(NS, 'title');
    tip.textContent = `${l.parent} -> ${l.child}: ${l.callCount} calls, ${l.errorCount || 0} errors`;
    path.append(tip);
    svg.append(path);
    // direction tick at 70% along the curve
    const tx = 0.09 * p[0] + 0.42 * mx + 0.49 * c[0],
      ty = 0.09 * p[1] + 0.42 * my + 0.49 * c[1];
    svg.append(el('circle', {
      cx: tx, cy: ty, r: Math.max(w, 1.6),
      fill: l.errorCount ? '#b71c1c' : '#3f51b5',
    }));
  }
  for (const n of names) {
    const [x, y] = pos.get(n);
    const dot = el('circle', { cx: x, cy: y, r: 6, fill: svcColor(n), cursor: 'pointer' });
    dot.addEventListener('click', () => serviceDetail(n));
    svg.append(dot);
    const label = el('text', {
      x: x + (x >= cx ? 9 : -9), y: y + 4, 'font-size': '11',
      'text-anchor': x >= cx ? 'start' : 'end', fill: '#222', cursor: 'pointer',
    });
    label.textContent = n;  // textContent: no markup interpretation
    label.addEventListener('click', () => serviceDetail(n));
    svg.append(label);
  }
  if (all.length > names.length) {
    const note = el('text', { x: 10, y: 20, 'font-size': '12', fill: '#b71c1c' });
    note.textContent = `${all.length - names.length} lower-volume services not shown (full list in the table below)`;
    svg.append(note);
  }
}

/* -------------------------------------------------------------- sketches */

VIEWS.set('sketches', async (args, params) => {
  const el = $('#view');
  el.innerHTML = `
  <section><h2>Latency percentiles
    <span class="muted">served from the device t-digest / histogram sketches</span></h2>
   <label>window: <select id="pctwin">
    <option value="">all time (digest)</option>
    <option value="3600000">last hour (sliced histograms)</option>
    <option value="86400000">last day (sliced histograms)</option>
   </select></label>
   <button id="pctrefresh" class="primary">refresh</button>
   <table id="pcttab"></table>
  </section>
  <section><h2>Trace cardinalities <span class="muted">device HLL estimates</span></h2>
   <table id="cardtab"></table>
  </section>
  <section><h2>Ingest counters
    <span class="muted">host-mirrored exact counters · <a href="/metrics">/metrics</a> ·
    <a href="/prometheus">/prometheus</a></span></h2>
   <button id="snap">snapshot now</button> <span id="snapout" class="muted"></span>
   <table id="ctrtab"></table>
  </section>`;
  $('#pctrefresh').addEventListener('click', loadPcts);
  $('#snap').addEventListener('click', async () => {
    const out = $('#snapout');
    try {
      const r = await fetch('/api/v2/tpu/snapshot', { method: 'POST' });
      out.textContent = r.ok ? 'saved: ' + (await r.json()).snapshot : 'HTTP ' + r.status + ': ' + await r.text();
    } catch (e) { out.textContent = String(e); }
  });
  await loadOverview();
});

let _pctSort = 'count';
function renderPcts(rows) {
  const t = $('#pcttab');
  const key = { count: r => -r.count, p50: r => -r.quantiles['0.5'], p99: r => -r.quantiles['0.99'],
    service: r => r.serviceName }[_pctSort] || (r => -r.count);
  rows.sort((a, b) => { const x = key(a), y = key(b); return x < y ? -1 : x > y ? 1 : 0; });
  let h = `<tr><th class="sortable" data-k="service">service</th><th>span</th>
    <th class="sortable" data-k="count">count</th><th class="sortable" data-k="p50">p50</th>
    <th>p90</th><th class="sortable" data-k="p99">p99</th></tr>`;
  for (const x of rows.slice(0, 500)) {
    h += `<tr><td><span class="svc-dot" style="background:${svcColor(x.serviceName)}"></span>${esc(x.serviceName)}</td>
      <td>${esc(x.spanName)}</td><td>${esc(x.count)}</td>
      <td>${esc(fmtDur(Math.round(x.quantiles['0.5'])))}</td>
      <td>${esc(fmtDur(Math.round(x.quantiles['0.9'])))}</td>
      <td>${esc(fmtDur(Math.round(x.quantiles['0.99'])))}</td></tr>`;
  }
  if (rows.length > 500) h += `<tr><td class="muted" colspan="6">${rows.length - 500} more rows not shown</td></tr>`;
  t.innerHTML = h;
  t.querySelectorAll('th.sortable').forEach(th =>
    th.addEventListener('click', () => { _pctSort = th.dataset.k; loadPcts(); }));
}

async function loadPcts() {
  const gen = _gen;
  const t = $('#pcttab');
  const win = $('#pctwin').value;
  // no window = the all-time digest view, which the coalesced overview
  // serves (with cards + counters) in ONE request and one device pull
  if (!win) return loadOverview();
  const q = '/api/v2/tpu/percentiles?q=0.5,0.9,0.99&lookback=' + win;
  let rows;
  try { rows = await get(q); }
  catch (e) { if (!stale(gen)) t.innerHTML = '<tr><td class="muted">TPU storage not enabled</td></tr>'; return; }
  if (stale(gen)) return;
  renderPcts(rows);
}

function renderCards(cards) {
  const t = $('#cardtab');
  let h = '<tr><th>service</th><th>distinct traces (est.)</th></tr>';
  const entries = Object.entries(cards).sort((a, b) => b[1] - a[1]);
  for (const [name, n] of entries) {
    const label = name === '_global' ? '(all services)' : name;
    h += `<tr><td>${name === '_global' ? '<b>' + esc(label) + '</b>' : esc(label)}</td>
      <td>${Math.round(n).toLocaleString()}</td></tr>`;
  }
  t.innerHTML = h;
}

function renderCounters(ctr) {
  const t = $('#ctrtab');
  let h = '<tr><th>counter</th><th>value</th></tr>';
  for (const k of Object.keys(ctr).sort())
    h += `<tr><td>${esc(k)}</td><td>${Number(ctr[k]).toLocaleString()}</td></tr>`;
  t.innerHTML = h;
}

async function loadOverview() {
  const gen = _gen;
  try {
    const o = await get('/api/v2/tpu/overview?q=0.5,0.9,0.99');
    if (stale(gen)) return;
    renderPcts(o.percentiles);
    renderCards(o.cardinalities);
    renderCounters(o.counters);
  } catch (e) {
    if (stale(gen)) return;
    // older server without the coalesced endpoint: three requests
    await loadLegacyPcts();
    await loadCards();
    await loadCounters();
  }
}

async function loadLegacyPcts() {
  const gen = _gen;
  const t = $('#pcttab');
  let rows;
  try { rows = await get('/api/v2/tpu/percentiles?q=0.5,0.9,0.99'); }
  catch (e) { if (!stale(gen)) t.innerHTML = '<tr><td class="muted">TPU storage not enabled</td></tr>'; return; }
  if (stale(gen)) return;
  renderPcts(rows);
}

async function loadCards() {
  const gen = _gen;
  const t = $('#cardtab');
  try {
    const cards = await get('/api/v2/tpu/cardinalities');
    if (stale(gen)) return;
    renderCards(cards);
  } catch (e) { if (!stale(gen)) t.innerHTML = '<tr><td class="muted">TPU storage not enabled</td></tr>'; }
}

async function loadCounters() {
  const gen = _gen;
  const t = $('#ctrtab');
  try {
    const ctr = await get('/api/v2/tpu/counters');
    if (stale(gen)) return;
    renderCounters(ctr);
  } catch (e) { if (stale(gen)) return; t.innerHTML = '<tr><td class="muted">TPU storage not enabled</td></tr>'; }
}

boot();
