"""Minimal built-in UI served at /zipkin/.

The reference serves the Lens React bundle from the server jar
(SURVEY.md §2.5); the rebuild keeps **API-shape compatibility** so Lens
itself can be pointed at this server, and ships this small dependency-free
page for the same three views (search, trace detail, dependencies) plus
the TPU percentile extension — consuming only the public JSON API.
"""

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>zipkin-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#fafafa;color:#222}
 header{background:#1a237e;color:#fff;padding:10px 16px;display:flex;gap:16px;align-items:center}
 header h1{font-size:16px;margin:0}
 main{padding:16px;max-width:1100px;margin:auto}
 section{background:#fff;border:1px solid #ddd;border-radius:6px;padding:12px;margin-bottom:16px}
 h2{font-size:14px;margin:0 0 8px}
 table{border-collapse:collapse;width:100%;font-size:13px}
 td,th{border-bottom:1px solid #eee;padding:4px 6px;text-align:left}
 .bar{background:#3f51b5;height:10px;border-radius:2px}
 .err{color:#b71c1c}
 select,input,button{font-size:13px;padding:3px 6px}
 .muted{color:#777}
</style></head><body>
<header><h1>zipkin-tpu</h1><span id="info" class="muted"></span></header>
<main>
<section><h2>Find traces</h2>
 <select id="svc"><option value="">all services</option></select>
 <input id="limit" type="number" value="10" style="width:4em">
 <button onclick="findTraces()">search</button>
 <div id="traces"></div>
 <div id="detail"></div>
</section>
<section><h2>Dependencies</h2><button onclick="deps()">refresh</button>
 <table id="deptab"><tr><th>parent</th><th>child</th><th>calls</th><th>errors</th></tr></table>
</section>
<section><h2>Latency percentiles (TPU sketches)</h2><button onclick="pcts()">refresh</button>
 <table id="pcttab"><tr><th>service</th><th>span</th><th>count</th><th>p50 µs</th><th>p99 µs</th></tr></table>
</section>
</main>
<script>
const $=q=>document.querySelector(q);
const get=async p=>{const r=await fetch(p);if(!r.ok)throw new Error(p+': '+r.status);return r.json()};
// span fields are attacker-controlled (anyone can POST to the collector):
// everything interpolated into markup goes through esc(), and trace ids
// are validated as hex before being used in an onclick.
const esc=s=>String(s??'').replace(/[&<>"'`]/g,c=>'&#'+c.charCodeAt(0)+';');
const hexOnly=s=>/^[0-9a-f]{1,32}$/.test(s)?s:'';
async function boot(){
  try{const i=await get('/info');$('#info').textContent='v'+i.zipkin.version;}catch(e){}
  try{const s=await get('/api/v2/services');
    for(const n of s){const o=document.createElement('option');o.value=o.textContent=n;$('#svc').append(o)}}catch(e){}
}
async function findTraces(){
  const svc=$('#svc').value, lim=$('#limit').value||10;
  const q=new URLSearchParams({endTs:Date.now(),lookback:7*864e5,limit:lim});
  if(svc)q.set('serviceName',svc);
  const traces=await get('/api/v2/traces?'+q);
  const el=$('#traces');el.innerHTML='';
  const t=document.createElement('table');
  t.innerHTML='<tr><th>trace</th><th>spans</th><th>duration µs</th><th></th></tr>';
  for(const tr of traces){
    const root=tr.reduce((a,b)=>(a.timestamp||1e18)<(b.timestamp||1e18)?a:b);
    const id=hexOnly(root.traceId);
    const row=document.createElement('tr');
    row.innerHTML=`<td>${esc(id)}</td><td>${tr.length}</td><td>${esc(root.duration||'')}</td>
      <td><button onclick="detail('${id}')">view</button></td>`;
    t.append(row);
  }
  el.append(t);
}
async function detail(id){
  const spans=await get('/api/v2/trace/'+id);
  const t0=Math.min(...spans.map(s=>s.timestamp||1e18));
  const total=Math.max(...spans.map(s=>(s.timestamp||t0)+(s.duration||0)))-t0||1;
  const el=$('#detail');
  let h=`<h2>trace ${esc(hexOnly(id))}</h2><table><tr><th>service</th><th>span</th><th>timeline</th><th>µs</th></tr>`;
  for(const s of spans.sort((a,b)=>(a.timestamp||0)-(b.timestamp||0))){
    const off=100*((s.timestamp||t0)-t0)/total, w=Math.max(100*(s.duration||0)/total,0.5);
    const err=s.tags&&s.tags.error!==undefined;
    h+=`<tr class="${err?'err':''}"><td>${esc((s.localEndpoint||{}).serviceName||'')}</td>
      <td>${esc(s.name||'')} ${esc(s.kind||'')}</td>
      <td style="width:50%"><div class="bar" style="margin-left:${off}%;width:${w}%"></div></td>
      <td>${esc(s.duration||'')}</td></tr>`;
  }
  el.innerHTML=h+'</table>';
}
async function deps(){
  const links=await get('/api/v2/dependencies?endTs='+Date.now()+'&lookback='+7*864e5);
  const t=$('#deptab');t.innerHTML='<tr><th>parent</th><th>child</th><th>calls</th><th>errors</th></tr>';
  for(const l of links){const r=document.createElement('tr');
    r.innerHTML=`<td>${esc(l.parent)}</td><td>${esc(l.child)}</td><td>${esc(l.callCount)}</td>
      <td class="${l.errorCount?'err':''}">${esc(l.errorCount||0)}</td>`;t.append(r)}
}
async function pcts(){
  try{
    const rows=await get('/api/v2/tpu/percentiles?q=0.5,0.99');
    const t=$('#pcttab');t.innerHTML='<tr><th>service</th><th>span</th><th>count</th><th>p50 µs</th><th>p99 µs</th></tr>';
    for(const x of rows){const r=document.createElement('tr');
      r.innerHTML=`<td>${esc(x.serviceName)}</td><td>${esc(x.spanName)}</td><td>${esc(x.count)}</td>
        <td>${Math.round(x.quantiles['0.5'])}</td><td>${Math.round(x.quantiles['0.99'])}</td>`;t.append(r)}
  }catch(e){$('#pcttab').innerHTML='<tr><td class="muted">TPU storage not enabled</td></tr>'}
}
boot();
</script></body></html>
"""
