"""Built-in UI served at /zipkin/ — a hash-routed single-page app.

The reference serves the Lens React bundle from the server jar
(SURVEY.md §2.5: zipkin-lens, ~20k LoC TS/React, consuming only the L4
JSON API). The rebuild keeps **API-shape compatibility** (pinned by
tests/test_lens_conformance.py) so Lens itself can be pointed at this
server, and ships this dependency-free app for the same views:

- Discover: service/spanName/annotationQuery/duration search with
  shareable URLs, per-trace service-share duration bars.
- Trace detail: Lens-style waterfall (shared-span nesting, DFS order),
  collapsible subtrees, minimap, timeline ruler, span-detail panel with
  sketch-served duration-percentile context.
- Dependencies: animated-graph equivalent (SVG call graph) + per-service
  callers/callees panel, fed solely by GET /api/v2/dependencies.
- TPU sketches: the rebuild's extension views (device percentiles,
  HLL cardinalities, ingest counters, snapshot trigger).

Assets are plain files under static/ (no build step — the deploy box
cannot run npm, and a 3-file vanilla app keeps the attack surface
reviewable: every payload-derived string is escaped, see app.js header).
"""

import mimetypes
import os
from typing import Optional

STATIC_DIR = os.path.join(os.path.dirname(__file__), "static")

_ASSETS = ("index.html", "app.js", "style.css")
_cache: dict = {}


def asset(name: str) -> Optional[tuple]:
    """(bytes, content_type) for a bundled asset, or None.

    Only names in the fixed allowlist resolve — the request path never
    touches the filesystem, so traversal is structurally impossible.
    """
    if name not in _ASSETS:
        return None
    if name not in _cache:
        with open(os.path.join(STATIC_DIR, name), "rb") as f:
            body = f.read()
        ctype = mimetypes.guess_type(name)[0] or "application/octet-stream"
        _cache[name] = (body, ctype)
    return _cache[name]


def index_page() -> str:
    body, _ = asset("index.html")
    return body.decode("utf-8")
