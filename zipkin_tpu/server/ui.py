"""Minimal built-in UI served at /zipkin/.

The reference serves the Lens React bundle from the server jar
(SURVEY.md §2.5); the rebuild keeps **API-shape compatibility** so Lens
itself can be pointed at this server, and ships this small dependency-free
page for the same three views (search, trace detail with a span-detail
panel and sketch-served duration-percentile context, dependencies) plus
the TPU percentile extension — consuming only the public JSON API.
"""

PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>zipkin-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#fafafa;color:#222}
 header{background:#1a237e;color:#fff;padding:10px 16px;display:flex;gap:16px;align-items:center}
 header h1{font-size:16px;margin:0}
 main{padding:16px;max-width:1100px;margin:auto}
 section{background:#fff;border:1px solid #ddd;border-radius:6px;padding:12px;margin-bottom:16px}
 h2{font-size:14px;margin:0 0 8px}
 table{border-collapse:collapse;width:100%;font-size:13px}
 td,th{border-bottom:1px solid #eee;padding:4px 6px;text-align:left}
 .bar{background:#3f51b5;height:10px;border-radius:2px}
 .bar.err{background:#b71c1c}
 .err{color:#b71c1c}
 .slow{color:#e65100;font-weight:600}
 select,input,button{font-size:13px;padding:3px 6px}
 .muted{color:#777}
 tr.srow{cursor:pointer}
 tr.srow:hover{background:#f0f2ff}
 #spanpanel{position:fixed;right:0;top:0;bottom:0;width:360px;background:#fff;
  border-left:2px solid #1a237e;padding:12px;overflow:auto;box-shadow:-2px 0 8px #0002;display:none}
 #spanpanel h3{margin:0 0 8px;font-size:14px}
 #spanpanel table{font-size:12px}
 #spanpanel .close{float:right}
</style></head><body>
<header><h1>zipkin-tpu</h1><span id="info" class="muted"></span></header>
<main>
<section><h2>Find traces</h2>
 <select id="svc" onchange="loadNames()"><option value="">all services</option></select>
 <select id="spanname"><option value="">all spans</option></select>
 <input id="annq" placeholder="annotationQuery: error and http.method=GET" style="width:22em">
 <input id="mindur" type="number" placeholder="min µs" style="width:6em">
 <input id="maxdur" type="number" placeholder="max µs" style="width:6em">
 <select id="lookback">
  <option value="3600000">last hour</option>
  <option value="86400000">last day</option>
  <option value="604800000" selected>last 7 days</option>
 </select>
 <input id="limit" type="number" value="10" style="width:4em">
 <button onclick="findTraces()">search</button>
 <span style="margin-left:12px">trace id:
  <input id="tid" placeholder="hex trace id" style="width:18em">
  <button onclick="gotoTrace()">open</button></span>
 <div id="traces"></div>
 <div id="detail"></div>
</section>
<section><h2>Dependencies</h2><button onclick="deps()">refresh</button>
 <svg id="depgraph" width="100%" height="0" viewBox="0 0 800 500"></svg>
 <table id="deptab"><tr><th>parent</th><th>child</th><th>calls</th><th>errors</th></tr></table>
</section>
<section><h2>Latency percentiles (TPU sketches)</h2><button onclick="pcts()">refresh</button>
 <table id="pcttab"><tr><th>service</th><th>span</th><th>count</th><th>p50 µs</th><th>p99 µs</th></tr></table>
</section>
</main>
<div id="spanpanel"></div>
<script>
const $=q=>document.querySelector(q);
const get=async p=>{const r=await fetch(p);if(!r.ok)throw new Error(p+': '+r.status);return r.json()};
// span fields are attacker-controlled (anyone can POST to the collector):
// everything interpolated into markup goes through esc(), and trace ids
// are validated as hex before being used in an onclick.
const esc=s=>String(s??'').replace(/[&<>"'`]/g,c=>'&#'+c.charCodeAt(0)+';');
const hexOnly=s=>/^[0-9a-f]{1,32}$/.test(s)?s:'';
async function boot(){
  try{const i=await get('/info');$('#info').textContent='v'+i.zipkin.version;}catch(e){}
  try{const s=await get('/api/v2/services');
    for(const n of s){const o=document.createElement('option');o.value=o.textContent=n;$('#svc').append(o)}}catch(e){}
}
async function loadNames(){
  // per-service span names for the spanName filter (the Lens discover
  // page's second dropdown)
  const svc=$('#svc').value, sel=$('#spanname');
  sel.innerHTML='<option value="">all spans</option>';
  if(!svc)return;
  try{const names=await get('/api/v2/spans?serviceName='+encodeURIComponent(svc));
    for(const n of names){const o=document.createElement('option');o.value=o.textContent=n;sel.append(o)}
  }catch(e){}
}
function gotoTrace(){
  const raw=$('#tid').value.trim().toLowerCase();
  const id=hexOnly(raw);
  const el=$('#detail');
  if(!id){el.innerHTML='<p class="err">not a hex trace id</p>';return}
  detail(id).catch(e=>{el.innerHTML='<p class="err">trace not found: '+esc(id)+'</p>'});
}
async function findTraces(){
  const svc=$('#svc').value, lim=$('#limit').value||10;
  const elq=$('#traces');
  const q=new URLSearchParams({endTs:Date.now(),
    lookback:$('#lookback').value||7*864e5,limit:lim});
  if(svc)q.set('serviceName',svc);
  const name=$('#spanname').value; if(name)q.set('spanName',name);
  const annq=$('#annq').value.trim(); if(annq)q.set('annotationQuery',annq);
  const mind=$('#mindur').value; if(mind)q.set('minDuration',mind);
  const maxd=$('#maxdur').value; if(maxd)q.set('maxDuration',maxd);
  let traces;
  try{traces=await get('/api/v2/traces?'+q)}
  catch(e){elq.innerHTML='<p class="err">search failed: '+esc(e.message)+
    ' (check the filter values)</p>';return}
  const el=elq;el.innerHTML='';
  if(!traces.length){el.innerHTML='<p class="muted">no traces matched</p>';return}
  const t=document.createElement('table');
  t.innerHTML='<tr><th>start</th><th>trace</th><th>services</th><th>spans</th><th>duration µs</th><th></th></tr>';
  for(const tr of traces){
    const root=tr.reduce((a,b)=>(a.timestamp||1e18)<(b.timestamp||1e18)?a:b);
    const id=hexOnly(root.traceId);
    const svcs=[...new Set(tr.map(s=>(s.localEndpoint||{}).serviceName).filter(Boolean))];
    const when=root.timestamp?new Date(root.timestamp/1000).toISOString().slice(0,19):'';
    const anyErr=tr.some(s=>s.tags&&s.tags.error!==undefined);
    const row=document.createElement('tr');
    row.innerHTML=`<td>${esc(when)}</td><td class="${anyErr?'err':''}">${esc(id)}</td>
      <td>${esc(svcs.slice(0,4).join(', '))}${svcs.length>4?' …':''}</td>
      <td>${tr.length}</td><td>${esc(root.duration||'')}</td>
      <td><button onclick="detail('${id}')">view</button></td>`;
    t.append(row);
  }
  el.append(t);
}
let curSpans=[];   // spans of the open trace, for the detail panel
let pctCtx={};     // (service|span) -> {p50, p99} percentile context
async function loadPctCtx(){
  if(Object.keys(pctCtx).length)return;
  try{const rows=await get('/api/v2/tpu/percentiles?q=0.5,0.99');
    for(const x of rows)pctCtx[x.serviceName+'|'+x.spanName]=
      {p50:x.quantiles['0.5'],p99:x.quantiles['0.99']};
  }catch(e){/* TPU sketches not enabled: waterfall renders without context */}
}
function treeOrder(spans){
  // Lens-style waterfall order: DFS over the span tree (parentId
  // edges; a shared SERVER span nests under its same-id client half),
  // children by timestamp; orphans (missing parents) surface as roots.
  // Returns [[span, depth], ...]. Cycle-safe via the visited set.
  const byId=new Map();
  for(const s of spans){const k=s.id;
    if(!byId.has(k))byId.set(k,[]);byId.get(k).push(s)}
  const parentOf=s=>{
    if(s.shared){  // server half: parent is the client half (same id)
      const mates=(byId.get(s.id)||[]).filter(m=>m!==s&&!m.shared);
      if(mates.length)return mates[0];
    }
    if(s.parentId&&byId.has(s.parentId)){
      // prefer the SHARED rendition (the server half is the closer
      // tree node — SpanNode's index preference), so server-created
      // children nest under the server span, not beside it
      const c=byId.get(s.parentId);
      return c.find(m=>m.shared)||c[0];
    }
    return null;
  };
  const kids=new Map(),roots=[];
  for(const s of spans){const p=parentOf(s);
    if(p){if(!kids.has(p))kids.set(p,[]);kids.get(p).push(s)}
    else roots.push(s)}
  const ts=s=>s.timestamp||1e18;
  roots.sort((a,b)=>ts(a)-ts(b));
  const out=[],seen=new Set();
  const walk=(s,d)=>{
    if(seen.has(s))return;seen.add(s);
    out.push([s,d]);
    const c=(kids.get(s)||[]).sort((a,b)=>ts(a)-ts(b));
    for(const k of c)walk(k,d+1);
  };
  for(const r of roots)walk(r,0);
  for(const s of spans)if(!seen.has(s))out.push([s,0]); // cycle leftovers
  return out;
}
async function detail(id){
  const spans=await get('/api/v2/trace/'+id);
  await loadPctCtx();
  const ordered=treeOrder(spans);
  curSpans=ordered.map(([s,_])=>s);
  const t0=Math.min(...spans.map(s=>s.timestamp||1e18));
  const total=Math.max(...spans.map(s=>(s.timestamp||t0)+(s.duration||0)))-t0||1;
  const svcs=new Set(spans.map(s=>(s.localEndpoint||{}).serviceName).filter(Boolean));
  const el=$('#detail');
  let h=`<h2>trace ${esc(hexOnly(id))}
    <span class="muted">${spans.length} spans · ${svcs.size} services ·
    ${Math.round(total)} µs (click a span for detail)</span></h2>
    <table><tr><th>service</th><th>span</th><th>timeline</th><th>µs</th><th>vs p99</th></tr>`;
  ordered.forEach(([s,depth],i)=>{
    const off=100*((s.timestamp||t0)-t0)/total, w=Math.max(100*(s.duration||0)/total,0.5);
    const err=s.tags&&s.tags.error!==undefined;
    const key=((s.localEndpoint||{}).serviceName||'')+'|'+(s.name||'');
    const ctx=pctCtx[key];
    // duration-percentile context from the device sketches (the Lens
    // "how slow is this span vs its peers" panel)
    let vs='';
    if(ctx&&s.duration){
      const r=s.duration/ctx.p99;
      vs=r>=1?`<span class="slow">${r.toFixed(1)}x p99</span>`
             :s.duration>=ctx.p50?'&gt;p50':'&lt;p50';
    }
    const pad=Math.min(depth,12)*14;
    const mark=depth?'<span class="muted">└ </span>':'';
    h+=`<tr class="srow ${err?'err':''}" onclick="spanDetail(${i})">
      <td style="padding-left:${6+pad}px">${mark}${esc((s.localEndpoint||{}).serviceName||'')}</td>
      <td>${esc(s.name||'')} ${esc(s.kind||'')}${s.shared?' <span class="muted">shared</span>':''}</td>
      <td style="width:45%"><div class="bar ${err?'err':''}" style="margin-left:${off}%;width:${w}%"></div></td>
      <td>${esc(s.duration||'')}</td><td>${vs}</td></tr>`;
  });
  el.innerHTML=h+'</table>';
}
function spanDetail(i){
  const s=curSpans[i];if(!s)return;
  const row=(k,v)=>v===undefined||v===''?'':`<tr><th>${esc(k)}</th><td>${esc(v)}</td></tr>`;
  const ep=e=>e?[e.serviceName,e.ipv4||e.ipv6,e.port].filter(Boolean).join(' '):'';
  let h=`<button class="close" onclick="$('#spanpanel').style.display='none'">×</button>
    <h3>${esc(s.name||'(unnamed)')} <span class="muted">${esc(s.kind||'')}</span></h3><table>`;
  h+=row('traceId',s.traceId)+row('spanId',s.id)+row('parentId',s.parentId)
    +row('shared',s.shared?'true':'')+row('timestamp µs',s.timestamp)
    +row('duration µs',s.duration)
    +row('local',ep(s.localEndpoint))+row('remote',ep(s.remoteEndpoint));
  const key=((s.localEndpoint||{}).serviceName||'')+'|'+(s.name||'');
  const ctx=pctCtx[key];
  if(ctx)h+=row('peer p50 µs',Math.round(ctx.p50))+row('peer p99 µs',Math.round(ctx.p99));
  h+='</table>';
  if(s.annotations&&s.annotations.length){
    h+='<h3>annotations</h3><table>';
    for(const a of s.annotations)h+=row(a.timestamp,a.value);
    h+='</table>';
  }
  const tags=s.tags||{};
  if(Object.keys(tags).length){
    h+='<h3>tags</h3><table>';
    for(const k of Object.keys(tags).sort())
      h+=`<tr><th class="${k==='error'?'err':''}">${esc(k)}</th><td>${esc(tags[k])}</td></tr>`;
    h+='</table>';
  }
  const p=$('#spanpanel');p.innerHTML=h;p.style.display='block';
}
async function deps(){
  const links=await get('/api/v2/dependencies?endTs='+Date.now()+'&lookback='+7*864e5);
  const t=$('#deptab');t.innerHTML='<tr><th>parent</th><th>child</th><th>calls</th><th>errors</th></tr>';
  for(const l of links){const r=document.createElement('tr');
    r.innerHTML=`<td>${esc(l.parent)}</td><td>${esc(l.child)}</td><td>${esc(l.callCount)}</td>
      <td class="${l.errorCount?'err':''}">${esc(l.errorCount||0)}</td>`;t.append(r)}
  depGraph(links);
}
function depGraph(links){
  // service graph (the Lens dependencies view): nodes on a circle,
  // directed edges with width ~ log(calls), red when errors flow.
  // Built with createElementNS + textContent only — span/service names
  // are attacker-controlled and never touch innerHTML here.
  const svg=$('#depgraph');const NS='http://www.w3.org/2000/svg';
  svg.innerHTML='';
  // rank services by call volume so a >48-service graph keeps the
  // heavy hitters, and SAY what was dropped (a silently truncated
  // graph reads as "those call paths do not exist"). Maps, not plain
  // objects: service names are attacker-controlled and "__proto__" /
  // "constructor" would corrupt object-keyed lookups.
  const vol=new Map();
  for(const l of links){vol.set(l.parent,(vol.get(l.parent)||0)+(l.callCount||0));
    vol.set(l.child,(vol.get(l.child)||0)+(l.callCount||0))}
  const all=[...vol.keys()].sort((a,b)=>vol.get(b)-vol.get(a));
  const names=all.slice(0,48);
  if(!names.length){svg.setAttribute('height','0');return}
  svg.setAttribute('height','500');
  const cx=400,cy=250,R=Math.min(200,60+names.length*8);
  const pos=new Map();
  names.forEach((n,i)=>{const a=2*Math.PI*i/names.length-Math.PI/2;
    pos.set(n,[cx+R*Math.cos(a),cy+R*Math.sin(a)])});
  const el=(k,at)=>{const e=document.createElementNS(NS,k);
    for(const[a,v]of Object.entries(at))e.setAttribute(a,v);return e};
  // reduce, not Math.max(...spread): a 100k-link response would blow
  // the JS argument-count limit
  const maxC=links.reduce((m,l)=>Math.max(m,l.callCount||1),1);
  for(const l of links){
    const p=pos.get(l.parent),c=pos.get(l.child);if(!p||!c)continue;
    const w=0.8+3*Math.log(1+(l.callCount||1))/Math.log(1+maxC);
    // curve through a point pulled toward the center so opposite-direction
    // edges between the same pair stay distinguishable
    const mx=(p[0]+c[0])/2+(cy-(p[1]+c[1])/2)*0.25,
          my=(p[1]+c[1])/2+((p[0]+c[0])/2-cx)*0.25;
    const path=el('path',{d:`M${p[0]},${p[1]} Q${mx},${my} ${c[0]},${c[1]}`,
      fill:'none',stroke:l.errorCount?'#b71c1c':'#7986cb','stroke-width':w,opacity:0.75});
    const tip=document.createElementNS(NS,'title');
    tip.textContent=`${l.parent} -> ${l.child}: ${l.callCount} calls, ${l.errorCount||0} errors`;
    path.append(tip);svg.append(path);
    // direction tick at 70% along the curve
    const tx=0.09*p[0]+0.42*mx+0.49*c[0],ty=0.09*p[1]+0.42*my+0.49*c[1];
    svg.append(el('circle',{cx:tx,cy:ty,r:Math.max(w,1.6),
      fill:l.errorCount?'#b71c1c':'#3f51b5'}));
  }
  for(const n of names){
    const[x,y]=pos.get(n);
    svg.append(el('circle',{cx:x,cy:y,r:5,fill:'#1a237e'}));
    const label=el('text',{x:x+(x>=cx?8:-8),y:y+4,'font-size':'11',
      'text-anchor':x>=cx?'start':'end',fill:'#222'});
    label.textContent=n;  // textContent: no markup interpretation
    svg.append(label);
  }
  if(all.length>names.length){
    const note=el('text',{x:10,y:20,'font-size':'12',fill:'#b71c1c'});
    note.textContent=`${all.length-names.length} lower-volume services not shown (full list in the table below)`;
    svg.append(note);
  }
}
async function pcts(){
  try{
    const rows=await get('/api/v2/tpu/percentiles?q=0.5,0.99');
    const t=$('#pcttab');t.innerHTML='<tr><th>service</th><th>span</th><th>count</th><th>p50 µs</th><th>p99 µs</th></tr>';
    for(const x of rows){const r=document.createElement('tr');
      r.innerHTML=`<td>${esc(x.serviceName)}</td><td>${esc(x.spanName)}</td><td>${esc(x.count)}</td>
        <td>${Math.round(x.quantiles['0.5'])}</td><td>${Math.round(x.quantiles['0.99'])}</td>`;t.append(r)}
  }catch(e){$('#pcttab').innerHTML='<tr><td class="muted">TPU storage not enabled</td></tr>'}
}
boot();
</script></body></html>
"""
