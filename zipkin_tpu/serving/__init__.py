"""Scale-out read serving (ISSUE 19).

The ingest process's mirror publisher serializes each epoch into a
shared-memory segment (`segment.py`); stateless reader processes map it
read-only and serve the query API without ever entering the ingest
process (`shape.py`, `reader.py`); a tiny supervisor spawns and
respawns them (`supervisor.py`, ``python -m zipkin_tpu.serving``).

Everything importable from a reader process is numpy + stdlib (+
aiohttp for the HTTP front end) — no jax, no store, no aggregator.
"""
