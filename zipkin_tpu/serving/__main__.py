"""``python -m zipkin_tpu.serving``: the multi-process reader front end.

Attaches the ingest process's mirror segment by name and runs the
reader supervisor in the foreground, plus a small aggregate HTTP
surface (``/metrics``, ``/prometheus``, ``/statusz``) on
``TPU_READER_PORT_BASE - 1`` that fans out to the reader-labeled
per-reader families.

Environment (validated by `server/config.py` when launched with the
ingest server; re-read here for the standalone front end):

- ``TPU_MIRROR_SEGMENT``      shm name the ingest server printed /
                              exposed in its ``/statusz`` serving block
                              (required)
- ``TPU_READERS``             reader process count (default 2)
- ``TPU_READER_PORT_BASE``    first reader port (default 9512)
"""

from __future__ import annotations

import json
import logging
import os
import sys

from aiohttp import web

from zipkin_tpu.serving.segment import MirrorSegment
from zipkin_tpu.serving.supervisor import ReaderSupervisor

logger = logging.getLogger(__name__)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def main() -> int:
    logging.basicConfig(level=logging.INFO)
    seg_name = os.environ.get("TPU_MIRROR_SEGMENT", "").strip()
    if not seg_name:
        print(
            "TPU_MIRROR_SEGMENT is required: the shm segment name the "
            "ingest server exposes in /api/v2/tpu/statusz under "
            '"serving.segment"', file=sys.stderr,
        )
        return 2
    readers = max(1, min(64, _env_int("TPU_READERS", 2)))
    port_base = _env_int("TPU_READER_PORT_BASE", 9512)
    segment = MirrorSegment(name=seg_name)
    sup = ReaderSupervisor(segment, readers, port_base)
    sup.start()

    async def get_metrics(request: web.Request) -> web.Response:
        return web.json_response(sup.scrape_metrics())

    async def get_prometheus(request: web.Request) -> web.Response:
        return web.Response(
            text=sup.scrape_prometheus(),
            content_type="text/plain", charset="utf-8",
        )

    async def get_statusz(request: web.Request) -> web.Response:
        return web.json_response(json.loads(json.dumps(sup.status())))

    async def on_cleanup(app_: web.Application) -> None:
        sup.stop()
        segment.close()

    async def supervise(app_: web.Application):
        import asyncio

        async def loop() -> None:
            while True:
                sup.poll()
                await asyncio.sleep(0.5)

        task = asyncio.create_task(loop())
        yield
        task.cancel()

    app = web.Application()
    app.router.add_get("/metrics", get_metrics)
    app.router.add_get("/prometheus", get_prometheus)
    app.router.add_get("/statusz", get_statusz)
    app.cleanup_ctx.append(supervise)
    app.on_cleanup.append(on_cleanup)
    logger.info(
        "serving front end: %d readers on %d.., aggregate on %d",
        readers, port_base, port_base - 1,
    )
    web.run_app(app, host="127.0.0.1", port=port_base - 1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
