"""Ingest-side segment publisher: mirror epoch → shared-memory payload.

Hooks the ReadMirror's ``segment_sink`` seam: after each mirror swap
(OUTSIDE the aggregator lock — the one-hold-per-tick invariant is the
mirror's, and serialization must never stretch it), the publisher
sanitizes the snapshot's raw read-program outputs into plain
dict/list/ndarray structures — nothing a reader would need the store,
jax, or any repo class to unpickle — and lands them in the segment
behind the seqlock stamp.

Sanitization is by mirror-key kind, tenant-prefix transparent: a
``tenant:<slug>:`` prefix is stripped for kind detection only, so
tenant-scoped planes serialize (and serve) exactly like the default
tenant's. Keys of unknown shape are skipped and counted — an epoch
must publish even when one registered closure returns something the
wire format does not know.

The publisher also owns the reverse demand path: ``drain_demand()``
empties every reader stripe each tick so `store.publish_mirror` can
re-register missed keys BEFORE the mirror cuts the next epoch — a
reader miss costs exactly one tick, like an in-process miss costs one
lock-path read.
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Dict, List, Optional

import numpy as np

from zipkin_tpu.model import json_v2
from zipkin_tpu.serving.segment import MirrorSegment

logger = logging.getLogger(__name__)


def split_tenant(key: str) -> tuple:
    """``("acme", "card")`` for ``tenant:acme:card``; ``(None, key)``
    otherwise."""
    if key.startswith("tenant:"):
        parts = key.split(":", 2)
        if len(parts) == 3 and parts[1]:
            return parts[1], parts[2]
    return None, key


# zt-lint: disable=ZT02 — not a device read: mirror snapshot values are
# already host arrays (the publisher pulled them packed, once, at epoch
# cut); np.asarray here only normalizes lists/scalars for pickling
def sanitize_value(key: str, value) -> Optional[tuple]:
    """One mirror value → its wire tuple ``(kind, ...)``, or None for
    a shape the format does not carry."""
    _, base = split_tenant(key)
    if base == "card":
        return ("card", np.asarray(value))
    if base.startswith("overview:"):
        source_q, counts, est = value
        return (
            "overview", np.asarray(source_q), np.asarray(counts),
            np.asarray(est),
        )
    if base.startswith("quant:"):
        source_q, counts = value
        return ("quant", np.asarray(source_q), np.asarray(counts))
    if base.startswith("deps:"):
        return ("deps", [json_v2.link_to_dict(x) for x in value])
    if base.startswith("ttq:"):
        return ("ttq", {
            "lo_ep": int(value.lo_ep),
            "hi_ep": int(value.hi_ep),
            "covered": int(value.covered),
            "missing": int(value.missing),
            "unsealed": bool(value.unsealed),
            "digest": np.asarray(value.digest),
            "hll": np.asarray(value.hll),
            "calls": np.asarray(value.calls),
            "errs": np.asarray(value.errs),
        })
    return None


def _plain_counters(counters: Dict) -> Dict:
    """Scalars only — the auto-rendered gauge subset (`/prometheus`
    skips nested tables the same way)."""
    return {
        k: v for k, v in counters.items()
        if isinstance(v, (int, float, bool, str))
    }


class SegmentPublisher:
    """The writer half: one ``publish_snapshot`` per mirror epoch."""

    def __init__(self, segment: MirrorSegment) -> None:
        self.segment = segment
        self.publishes = 0
        self.errors = 0
        self.skipped_keys = 0
        self.payload_bytes = 0
        self.serialize_ms = 0.0
        self.demand_drained = 0

    def publish_snapshot(
        self,
        snap,
        *,
        vocab,
        max_stale_ms: float,
        deps_max_stale_ms: float,
        time_bucket_minutes: int,
        global_hll_row: int,
        tt_sealed_through: Optional[int],
        counters: Dict,
        mirror_generation: int,
    ) -> bool:
        """Serialize + land one MirrorSnapshot. Never raises — a
        serialization failure is counted and the previous epoch keeps
        serving (same never-abort-the-epoch posture as the mirror's
        per-key compute guard)."""
        t0 = time.perf_counter()
        try:
            values: Dict[str, tuple] = {}
            for key, raw in snap.values.items():
                try:
                    wire = sanitize_value(key, raw)
                except (TypeError, ValueError, AttributeError):
                    wire = None
                if wire is None:
                    self.skipped_keys += 1
                    continue
                values[key] = wire
            with vocab._lock:
                key_list = np.asarray(vocab._key_list, np.int32)
            payload = pickle.dumps(
                {
                    "format": 1,
                    "mirror_generation": mirror_generation,
                    "write_version": snap.write_version,
                    "published_at": snap.published_at,
                    "publish_ms": snap.publish_ms,
                    "max_stale_ms": float(max_stale_ms),
                    "deps_max_stale_ms": float(deps_max_stale_ms),
                    "tt_enabled": tt_sealed_through is not None,
                    "tt_sealed_through": (
                        -1 if tt_sealed_through is None
                        else int(tt_sealed_through)
                    ),
                    "time_bucket_minutes": int(time_bucket_minutes),
                    "global_hll_row": int(global_hll_row),
                    "services": list(vocab.services._names),
                    "span_names": list(vocab.span_names._names),
                    "key_list": key_list,
                    "values": values,
                    "counters": _plain_counters(counters),
                },
                protocol=4,
            )
            ok = self.segment.write(
                payload,
                mirror_generation=mirror_generation,
                write_version=snap.write_version,
            )
            self.serialize_ms = (time.perf_counter() - t0) * 1000.0
            self.payload_bytes = len(payload)
            if ok:
                self.publishes += 1
            else:
                self.errors += 1
                logger.warning(
                    "mirror segment publish dropped: payload %d bytes "
                    "exceeds segment capacity %d",
                    len(payload), self.segment.capacity,
                )
            return ok
        except Exception:
            self.errors += 1
            logger.exception("mirror segment publish failed")
            return False

    def drain_demand(self) -> List[str]:
        keys = self.segment.demand_drain()
        self.demand_drained += len(keys)
        return keys

    def counters(self) -> Dict:
        """Flat gauges merged into ``store.ingest_counters`` → the
        ``/metrics`` serving block and the auto-rendered
        ``zipkin_tpu_segment_*`` / ``zipkin_tpu_reader_*`` families."""
        seg = self.segment.status()
        age_ms = 0.0
        lag = 0
        for r in seg["readers"]:
            if r["alive"]:
                age_ms = max(age_ms, r["lastServeAgeMs"])
                lag = max(lag, r["generationLag"])
        return {
            "segmentPublishes": self.publishes,
            "segmentPublishErrors": self.errors,
            "segmentOverflows": seg["overflows"],
            "segmentSkippedKeys": self.skipped_keys,
            "segmentPayloadBytes": self.payload_bytes,
            "segmentSerializeMs": round(self.serialize_ms, 3),
            "segmentGeneration": seg["generation"],
            "readerRespawns": seg["respawns"],
            "readerDemandRequests": self.demand_drained,
            "readerDemandOverflow": sum(
                r["demandOverflow"] for r in seg["readers"]
            ),
            "readerServeAgeMs": age_ms,
            "readerGenerationLagMax": lag,
        }
