"""Stateless reader process: the async HTTP front end over one SegmentView.

Each reader maps the mirror segment read-only and serves the four
sketch read endpoints on its own port — byte-compatible routes and
parameters with `server/app.py` (``/api/v2/dependencies``,
``/api/v2/tpu/percentiles|cardinalities|overview``), plus ``/metrics``
and ``/prometheus`` for the supervisor's reader-labeled aggregation.
Queries never enter the ingest process: every answer comes from the
shared-memory epoch, stamped with its real staleness
(``X-Staleness-Ms``), and anything the epoch cannot answer within
bounds is a 503 with Retry-After — a mirror-key miss (demanded back to
the publisher, carried next tick), an over-bound epoch age, a
requested-fresh read, or a segment torn/unpublished too long. Never a
silent stale answer.

Serves run directly on the asyncio loop — a serve is a header-word
compare plus a dict hit on the per-generation memo, so there is
nothing to offload to a thread (and no lock for one to contend on;
ZT13 proves the whole chain lock-free statically).

Spawn entry: :func:`run_reader` (module-level, importable without jax).
"""

from __future__ import annotations

import math
import os
import re
import time
from typing import Optional

from aiohttp import web

from zipkin_tpu.serving.segment import MirrorSegment, SegmentUnavailable
from zipkin_tpu.serving.shape import (
    SegmentMiss, SegmentView, StalenessExceeded,
)

_RETRY_AFTER_S = 1  # one publish tick; misses and swaps resolve by then


def _unavailable(reason: str, retry_after_s: int = _RETRY_AFTER_S,
                 **headers) -> web.Response:
    h = {"Retry-After": str(retry_after_s)}
    h.update({k: str(v) for k, v in headers.items()})
    return web.Response(status=503, text=reason, headers=h)


_CAMEL = re.compile(r"(?<!^)(?=[A-Z])")


def _snake(name: str) -> str:
    return _CAMEL.sub("_", name).lower()


class ReaderApp:
    """One reader's handlers; state is the SegmentView alone."""

    def __init__(self, view: SegmentView, port: int = 0,
                 default_lookback: int = 86400000) -> None:
        self.view = view
        self.port = port
        self.default_lookback = default_lookback
        self.started_at = time.monotonic()

    def build(self) -> web.Application:
        app = web.Application()
        r = app.router
        r.add_get("/api/v2/dependencies", self.get_dependencies)
        r.add_get("/api/v2/tpu/percentiles", self.get_percentiles)
        r.add_get("/api/v2/tpu/cardinalities", self.get_cardinalities)
        r.add_get("/api/v2/tpu/overview", self.get_overview)
        r.add_get("/health", self.get_health)
        r.add_get("/metrics", self.get_metrics)
        r.add_get("/prometheus", self.get_prometheus)
        return app

    # -- request plumbing --------------------------------------------------

    @staticmethod
    def _staleness_param(request: web.Request) -> Optional[float]:
        raw = request.query.get("staleness_ms")
        return float(raw) if raw is not None else None

    def _serve(self, fn, *args, **kwargs) -> web.Response:  # zt-reader-process: the 503 contract — miss/over-bound/torn all surface, none serve silently
        try:
            body, age_ms = fn(*args, **kwargs)
        except SegmentMiss as e:
            self.view.errors += 1
            return _unavailable(
                f"epoch does not carry {e.key!r} yet"
                + ("; registered for the next publish" if e.registered
                   else "; demand stripe full, retry"),
            )
        except StalenessExceeded as e:
            self.view.errors += 1
            if e.fresh_required:
                return _unavailable(
                    "staleness_ms<=0 demands a fresh read; readers serve "
                    "published epochs only — query the ingest server",
                )
            return _unavailable(
                f"epoch age {e.age_ms:.1f}ms exceeds bound "
                f"{e.bound_ms:.1f}ms",
                retry_after_s=max(
                    _RETRY_AFTER_S,
                    int(math.ceil((e.age_ms - e.bound_ms) / 1000.0)),
                ),
            )
        except SegmentUnavailable as e:
            self.view.unavailable += 1
            self.view.errors += 1
            return _unavailable(
                f"segment unavailable: {e.reason}",
                **{"X-Writer-Alive": int(e.writer_alive)},
            )
        return web.json_response(
            body, headers={"X-Staleness-Ms": f"{age_ms:.3f}"}
        )

    # -- endpoints ---------------------------------------------------------

    async def get_dependencies(self, request: web.Request) -> web.Response:
        raw_end = request.query.get("endTs")
        if not raw_end:
            return web.Response(status=400, text="endTs parameter is required")
        try:
            end_ts = int(raw_end)
            lookback = int(
                request.query.get("lookback") or self.default_lookback
            )
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        return self._serve(
            self.view.serve_dependencies, end_ts, lookback, staleness,
            request.query.get("tenant"),
        )

    async def get_percentiles(self, request: web.Request) -> web.Response:
        raw_q = request.query.get("q", "0.5,0.9,0.99")
        try:
            qs = [float(x) for x in raw_q.split(",") if x]
            if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
                raise ValueError(f"q out of range: {raw_q!r}")
            end_ts = request.query.get("endTs")
            lookback = request.query.get("lookback")
            end_ts = int(end_ts) if end_ts is not None else None
            lookback = int(lookback) if lookback is not None else None
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        return self._serve(
            self.view.serve_quantiles,
            qs,
            request.query.get("serviceName"),
            request.query.get("spanName"),
            request.query.get("sketch", "digest") == "digest",
            end_ts,
            lookback,
            staleness,
            request.query.get("tenant"),
        )

    async def get_cardinalities(self, request: web.Request) -> web.Response:
        try:
            staleness = self._staleness_param(request)
            end_ts = request.query.get("endTs")
            lookback = request.query.get("lookback")
            end_ts = int(end_ts) if end_ts is not None else None
            lookback = int(lookback) if lookback is not None else None
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        return self._serve(
            self.view.serve_cardinalities, staleness, end_ts, lookback,
            request.query.get("tenant"),
        )

    async def get_overview(self, request: web.Request) -> web.Response:
        raw_q = request.query.get("q", "0.5,0.9,0.99")
        try:
            qs = [float(x) for x in raw_q.split(",") if x]
            if not qs or any(not (0.0 <= q <= 1.0) for q in qs):
                raise ValueError(f"q out of range: {raw_q!r}")
            staleness = self._staleness_param(request)
        except ValueError as e:
            return web.Response(status=400, text=str(e))
        return self._serve(
            self.view.serve_overview,
            qs,
            request.query.get("serviceName"),
            request.query.get("spanName"),
            staleness,
            request.query.get("tenant"),
        )

    # -- ops ---------------------------------------------------------------

    async def get_health(self, request: web.Request) -> web.Response:
        try:
            self.view.refresh()
        except SegmentUnavailable as e:
            return web.json_response(
                {"status": "DOWN", "reason": e.reason},
                status=503, headers={"Retry-After": str(_RETRY_AFTER_S)},
            )
        return web.json_response({
            "status": "UP",
            "reader": f"r{self.view.reader_idx}",
            "generation": self.view.counters()["readerGeneration"],
        })

    async def get_metrics(self, request: web.Request) -> web.Response:
        body = dict(self.view.counters())
        body["readerPid"] = os.getpid()
        body["readerPort"] = self.port
        body["readerUptimeS"] = round(
            time.monotonic() - self.started_at, 3
        )
        return web.json_response({"reader": body})

    async def get_prometheus(self, request: web.Request) -> web.Response:
        label = f'reader="r{self.view.reader_idx}"'
        lines = []
        for name, value in self.view.counters().items():
            if isinstance(value, bool):
                value = int(value)
            if not isinstance(value, (int, float)):
                continue
            lines.append(
                f"zipkin_tpu_{_snake(name)}{{{label}}} {value}"
            )
        return web.Response(
            text="\n".join(lines) + "\n",
            content_type="text/plain", charset="utf-8",
        )


def run_reader(
    seg_params: dict,
    reader_idx: int,
    port: int,
    default_lookback: int = 86400000,
) -> None:  # zt-reader-process: spawn entry — attaches the segment and serves; imports numpy/stdlib/aiohttp, never jax or the store
    """Blocking reader main (the supervisor's spawn target)."""
    segment = MirrorSegment.attach(seg_params)
    view = SegmentView(segment, reader_idx)
    app = ReaderApp(view, port=port, default_lookback=default_lookback)
    try:
        web.run_app(
            app.build(), host="127.0.0.1", port=port,
            print=None, handle_signals=True,
        )
    finally:
        # drop the numpy control-word views before interpreter shutdown
        # GCs the SharedMemory object — otherwise its __del__ races the
        # exported buffer pointers and spams BufferError on every exit
        segment.close()
