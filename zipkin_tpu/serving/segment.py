"""Shared-memory mirror segment: the ingest→reader epoch seam.

One shm block carries the mirror's published epoch across process
boundaries, behind the PR 6 seqlock idiom the span ring (`tpu/ring.py`)
already fuzz-proves: the writer stamps the generation ODD before
touching the header, EVEN after, and readers spin-retry a torn (odd or
moved) generation. Two payload buffers alternate so a reader mid-copy
of the live buffer is never overwritten by the next publish — the
writer always lands in the inactive one — and a CRC32 over the payload
is the cross-process backstop the in-process seqlock never needed: a
reader that raced TWO publishes (its buffer reused underneath it)
fails the CRC and retries.

Writer death is detectable, never silent: the writer pid lives in the
header, and a generation stuck odd with a dead pid means the ingest
process died mid-publish — readers raise :class:`SegmentUnavailable`
(the 503 Retry-After path) instead of serving the torn epoch.

Reader→writer backchannel: per-reader SPSC demand stripes (the ring's
striped-ownership topology) let a reader register a missed mirror key
back to the publisher without any cross-process lock — reader writes
the key then advances its head (the release fence); the publisher
drains below the head at each tick. Next to each stripe sit heartbeat
words (pid, last generation seen, serve counters) feeding the ingest
``/statusz`` serving block.

This module is imported by reader processes: numpy + stdlib only,
no jax.
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

SEG_MAGIC = 0x5A54534D  # 'ZTSM'

# header words (int64)
H_MAGIC = 0
H_GEN = 1         # seqlock generation: odd while a publish is landing
H_BUF = 2         # active payload buffer (0/1)
H_LEN = 3         # payload length, bytes
H_CRC = 4         # crc32 of the payload
H_PID = 5         # writer (ingest) pid — the liveness guard
H_PUB_NS = 6      # time.monotonic_ns() at publish (cross-process on Linux)
H_WALL_MS = 7     # wall clock ms at publish
H_MGEN = 8        # mirror generation the payload was cut from
H_WVER = 9        # aggregator write_version of the epoch
H_PUBLISHES = 10  # total segment publishes
H_CAP = 11        # per-buffer payload capacity
H_READERS = 12    # reader stripe count
H_SUP_PID = 13    # supervisor pid (0 = standalone readers)
H_RESPAWNS = 14   # supervisor respawn total
H_OVERFLOWS = 15  # publishes dropped: payload outgrew the buffer
H_DEMAND_SLOTS = 16  # geometry, so attach-by-name needs no side channel
H_KEY_CAP = 17
HDR_WORDS = 18

# per-reader heartbeat words, then the SPSC demand (head, tail) pair
R_PID = 0
R_GEN_SEEN = 1    # segment generation at the reader's last serve
R_SERVE_NS = 2    # monotonic_ns of the last serve
R_SERVES = 3
R_AGE_US = 4      # staleness of the last serve, µs
R_DEMANDS = 5     # demand keys this reader pushed
R_DEMAND_OVF = 6  # pushes refused: stripe full
R_ERRORS = 7      # 503s this reader returned
HB_WORDS = 8
_D_HEAD = HB_WORDS      # reader-advanced (producer)
_D_TAIL = HB_WORDS + 1  # publisher-advanced (consumer)
STRIPE_WORDS = HB_WORDS + 2

DEFAULT_SEGMENT_BYTES = 4 << 20
DEFAULT_DEMAND_SLOTS = 32
DEFAULT_KEY_CAP = 120

# same cap family as the recorder/mirror seqlock readers; segment spins
# also sleep (another PROCESS holds the odd generation, so burning the
# reader's GIL slice cannot help the writer finish)
_TORN_RETRIES = 1000
_SPIN_SLEEP_S = 0.0002

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SegmentUnavailable(Exception):
    """No consistent epoch could be read: never published yet, torn
    past the retry budget, or the writer died mid-publish. The reader
    front end maps this to 503 + Retry-After — never a silent stale or
    torn answer."""

    def __init__(self, reason: str, *, torn: int = 0,
                 writer_alive: bool = False, gen: int = -1) -> None:
        super().__init__(reason)
        self.reason = reason
        self.torn = torn
        self.writer_alive = writer_alive
        self.gen = gen


class SegmentFrame:
    """One consistent copy of the published epoch (header + payload)."""

    __slots__ = (
        "payload", "gen", "mirror_generation", "write_version",
        "published_ns", "wall_ms", "publishes",
    )

    def __init__(self, payload: bytes, gen: int, mirror_generation: int,
                 write_version: int, published_ns: int, wall_ms: int,
                 publishes: int) -> None:
        self.payload = payload
        self.gen = gen
        self.mirror_generation = mirror_generation
        self.write_version = write_version
        self.published_ns = published_ns
        self.wall_ms = wall_ms
        self.publishes = publishes


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - exists, not ours
        return True
    return True


class MirrorSegment:
    """Owner/attach handle over one shared-memory mirror segment.

    The ingest process creates it (``name=None``); readers and the
    supervisor attach by name via :meth:`params`. All control state is
    int64 words on the mapped buffer — no cross-process lock exists
    anywhere, which is what lets a SIGKILL'd reader leave nothing to
    clean up (its demand stripe head simply stops moving).
    """

    def __init__(
        self,
        *,
        readers: int = 4,
        capacity: int = DEFAULT_SEGMENT_BYTES,
        demand_slots: int = DEFAULT_DEMAND_SLOTS,
        key_cap: int = DEFAULT_KEY_CAP,
        name: Optional[str] = None,
    ) -> None:
        from multiprocessing import shared_memory

        if name is not None:
            # attach: geometry comes from the creator's header words,
            # so a name alone (statusz, env var) is a complete address
            self._shm = shared_memory.SharedMemory(name=name)
            self._owner = False
            hdr = np.frombuffer(self._shm.buf, np.int64, count=HDR_WORDS)
            magic = int(hdr[H_MAGIC])
            readers = int(hdr[H_READERS])
            capacity = int(hdr[H_CAP])
            demand_slots = int(hdr[H_DEMAND_SLOTS])
            key_cap = int(hdr[H_KEY_CAP])
            del hdr  # the view must die before close() can unmap
            if magic != SEG_MAGIC:
                self._shm.close()
                raise ValueError(
                    f"shm block {name!r} is not a mirror segment"
                )
        self.readers = int(readers)
        self.capacity = int(capacity)
        self.demand_slots = int(demand_slots)
        self.key_cap = int(key_cap)
        self.slot_bytes = _align(8 + self.key_cap)
        self._ctl_words = HDR_WORDS + self.readers * STRIPE_WORDS
        self._slots_off = _align(self._ctl_words * 8)
        self._buf0_off = _align(
            self._slots_off
            + self.readers * self.demand_slots * self.slot_bytes
        )
        self._buf1_off = self._buf0_off + _align(self.capacity)
        total = self._buf1_off + _align(self.capacity)
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=total)
            self._owner = True
        self._a = np.frombuffer(
            self._shm.buf, np.int64, count=self._ctl_words
        )
        if self._owner:
            self._a[:] = 0
            self._a[H_MAGIC] = SEG_MAGIC
            self._a[H_CAP] = self.capacity
            self._a[H_READERS] = self.readers
            self._a[H_DEMAND_SLOTS] = self.demand_slots
            self._a[H_KEY_CAP] = self.key_cap
        self._closed = False

    @property
    def name(self) -> str:
        return self._shm.name

    def params(self) -> dict:
        """Spawn-safe attach info (the ring's ``params()`` contract)."""
        return {
            "name": self._shm.name,
            "readers": self.readers,
            "capacity": self.capacity,
            "demand_slots": self.demand_slots,
            "key_cap": self.key_cap,
        }

    @classmethod
    def attach(cls, params: dict) -> "MirrorSegment":
        return cls(
            readers=params["readers"],
            capacity=params["capacity"],
            demand_slots=params["demand_slots"],
            key_cap=params["key_cap"],
            name=params["name"],
        )

    # -- writer side (ingest process only) --------------------------------

    def write(
        self,
        payload: bytes,
        *,
        mirror_generation: int,
        write_version: int,
        wall_ms: Optional[int] = None,
    ) -> bool:
        """Publish one epoch: land the payload in the INACTIVE buffer,
        then seqlock-stamp the header around the swap. Returns False
        (counted, epoch dropped, previous one keeps serving) when the
        payload outgrew the buffer — a reader must never see a
        truncated pickle."""
        a = self._a
        if len(payload) > self.capacity:
            a[H_OVERFLOWS] += 1
            return False
        target = 1 - int(a[H_BUF])
        off = self._buf0_off if target == 0 else self._buf1_off
        self._shm.buf[off:off + len(payload)] = payload
        g = int(a[H_GEN])
        if g & 1:
            g += 1  # re-even a claim a crashed previous writer left
        a[H_GEN] = g + 1  # odd: publish landing
        a[H_BUF] = target
        a[H_LEN] = len(payload)
        a[H_CRC] = zlib.crc32(payload)
        a[H_PID] = os.getpid()
        a[H_PUB_NS] = time.monotonic_ns()
        a[H_WALL_MS] = (
            int(time.time() * 1000) if wall_ms is None else int(wall_ms)
        )
        a[H_MGEN] = int(mirror_generation)
        a[H_WVER] = int(write_version)
        a[H_PUBLISHES] += 1
        a[H_GEN] = g + 2  # even: stable
        return True

    # -- reader side (lock-free, any process) -----------------------------

    def generation(self) -> int:
        return int(self._a[H_GEN])

    def writer_alive(self) -> bool:
        return _pid_alive(int(self._a[H_PID]))

    def read_frame(
        self, spins: int = _TORN_RETRIES, spin_sleep_s: float = _SPIN_SLEEP_S
    ) -> SegmentFrame:  # zt-reader-process: seqlock spin + one buffer copy + CRC check — no lock of any kind, in any process
        """One consistent epoch copy via the seqlock read protocol,
        with the CRC as the two-publish-race backstop. Raises
        :class:`SegmentUnavailable` (the 503 path) when no consistent
        read lands inside the spin budget or nothing was published."""
        a = self._a
        torn = 0
        for attempt in range(spins):
            g1 = int(a[H_GEN])
            if g1 == 0:
                raise SegmentUnavailable(
                    "segment never published", gen=0,
                    writer_alive=self.writer_alive(),
                )
            if g1 & 1:
                if attempt >= 8:
                    time.sleep(spin_sleep_s)
                continue
            buf = int(a[H_BUF])
            length = int(a[H_LEN])
            crc = int(a[H_CRC])
            mgen = int(a[H_MGEN])
            wver = int(a[H_WVER])
            pub_ns = int(a[H_PUB_NS])
            wall_ms = int(a[H_WALL_MS])
            publishes = int(a[H_PUBLISHES])
            off = self._buf0_off if buf == 0 else self._buf1_off
            payload = bytes(self._shm.buf[off:off + length])
            if int(a[H_GEN]) != g1:
                torn += 1
                continue
            if zlib.crc32(payload) != crc:
                torn += 1
                continue
            return SegmentFrame(
                payload, g1, mgen, wver, pub_ns, wall_ms, publishes
            )
        raise SegmentUnavailable(
            "torn past the retry budget (writer "
            + ("mid-publish)" if self.writer_alive() else "died mid-publish)"),
            torn=torn, writer_alive=self.writer_alive(),
            gen=int(a[H_GEN]),
        )

    # -- demand backchannel (reader produces, publisher drains) -----------

    def _stripe_base(self, r: int) -> int:
        return HDR_WORDS + r * STRIPE_WORDS

    def _slot_off(self, r: int, seq: int) -> int:
        g = r * self.demand_slots + (seq % self.demand_slots)
        return self._slots_off + g * self.slot_bytes

    def demand_push(self, r: int, key: str) -> bool:  # zt-reader-process: SPSC stripe write — key bytes land before the head fence moves; no lock
        """Register a missed mirror key back to the publisher. Bounded:
        a full stripe refuses (counted by the caller) — a key-churning
        client cannot wedge its reader, only lose the registration."""
        a = self._a
        base = self._stripe_base(r)
        head = int(a[base + _D_HEAD])
        tail = int(a[base + _D_TAIL])
        if head - tail >= self.demand_slots:
            return False
        raw = key.encode("utf-8")[: self.key_cap]
        off = self._slot_off(r, head)
        self._shm.buf[off:off + 8] = len(raw).to_bytes(8, "little")
        self._shm.buf[off + 8:off + 8 + len(raw)] = raw
        a[base + _D_HEAD] = head + 1  # the release fence
        return True

    def demand_drain(self) -> List[str]:
        """Publisher side: every pushed key across all stripes. A
        reader SIGKILL'd mid-push left its head unmoved, so a torn
        slot is simply never visible here."""
        out: List[str] = []
        a = self._a
        for r in range(self.readers):
            base = self._stripe_base(r)
            head = int(a[base + _D_HEAD])
            tail = int(a[base + _D_TAIL])
            for seq in range(tail, head):
                off = self._slot_off(r, seq)
                n = int.from_bytes(self._shm.buf[off:off + 8], "little")
                n = max(0, min(n, self.key_cap))
                out.append(
                    bytes(self._shm.buf[off + 8:off + 8 + n])
                    .decode("utf-8", "replace")
                )
            if head != tail:
                a[base + _D_TAIL] = head
        return out

    # -- heartbeats / supervisor words ------------------------------------

    def heartbeat(
        self, r: int, *, gen_seen: int, serves: int, age_us: int,
        demands: int, demand_overflow: int, errors: int,
    ) -> None:  # zt-reader-process: plain word stores on the mapped buffer; torn reads tolerated (debug-gauge contract)
        a = self._a
        base = self._stripe_base(r)
        a[base + R_PID] = os.getpid()
        a[base + R_GEN_SEEN] = gen_seen
        a[base + R_SERVE_NS] = time.monotonic_ns()
        a[base + R_SERVES] = serves
        a[base + R_AGE_US] = age_us
        a[base + R_DEMANDS] = demands
        a[base + R_DEMAND_OVF] = demand_overflow
        a[base + R_ERRORS] = errors

    def reader_status(self) -> List[Dict]:
        """Per-reader heartbeat view for the ``/statusz`` serving block:
        generation lag, last serve age, liveness."""
        a = self._a
        now_ns = time.monotonic_ns()
        gen = int(a[H_GEN])
        out: List[Dict] = []
        for r in range(self.readers):
            base = self._stripe_base(r)
            pid = int(a[base + R_PID])
            serve_ns = int(a[base + R_SERVE_NS])
            out.append({
                "reader": f"r{r}",
                "pid": pid,
                "alive": _pid_alive(pid),
                "generationLag": max(0, gen - int(a[base + R_GEN_SEEN])),
                "serves": int(a[base + R_SERVES]),
                "lastServeAgeMs": round(int(a[base + R_AGE_US]) / 1000.0, 3),
                "sinceServeMs": (
                    round((now_ns - serve_ns) / 1e6, 3) if serve_ns else None
                ),
                "demandRequests": int(a[base + R_DEMANDS]),
                "demandOverflow": int(a[base + R_DEMAND_OVF]),
                "errors": int(a[base + R_ERRORS]),
                "demandQueued": int(a[base + _D_HEAD])
                - int(a[base + _D_TAIL]),
            })
        return out

    def note_supervisor(self, pid: int, respawns: int) -> None:
        self._a[H_SUP_PID] = pid
        self._a[H_RESPAWNS] = respawns

    def status(self) -> Dict:
        """Segment-level header view (ingest statusz + supervisor)."""
        a = self._a
        return {
            "name": self._shm.name,
            "bytes": self.capacity,
            "generation": int(a[H_GEN]),
            "publishes": int(a[H_PUBLISHES]),
            "overflows": int(a[H_OVERFLOWS]),
            "payloadBytes": int(a[H_LEN]),
            "mirrorGeneration": int(a[H_MGEN]),
            "writeVersion": int(a[H_WVER]),
            "writerPid": int(a[H_PID]),
            "writerAlive": self.writer_alive(),
            "supervisorPid": int(a[H_SUP_PID]),
            "respawns": int(a[H_RESPAWNS]),
            "readers": self.reader_status(),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._a = None
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover - traceback-pinned view
            # a live exception traceback (e.g. a caught
            # SegmentUnavailable) can pin a numpy view of the mapping
            # in its frame locals; let GC unmap later rather than
            # refusing to close — unlink below still retires the block
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass
