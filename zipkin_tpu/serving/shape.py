"""Reader-side serving: payload decode + the store's row shaping, replicated.

A :class:`SegmentView` wraps one attached :class:`MirrorSegment` and
serves the four read endpoints from the deserialized epoch payload —
the same mirror keys, the same route selection (time-tier vs minute
windows), and byte-identical row shaping to `tpu/store.py`'s
``_quantile_rows_inner`` / ``_cardinality_rows`` /
``_tt_dependency_links`` — so reader-vs-ingest parity at a shared
generation holds by construction (`tests/test_serving_parity.py`
enforces it endpoint by endpoint).

Staleness contract (the 503 half of the mirror's): every answer is
stamped with its real age (monotonic now − the epoch's publish
instant; CLOCK_MONOTONIC is cross-process comparable on Linux). An
age over the effective bound — the request's ``staleness_ms`` when
given, else the bound the publisher stamped into the payload — raises
:class:`StalenessExceeded`; ``staleness_ms <= 0`` (the fresh-read
escape hatch) always raises, because a reader process CANNOT serve
fresh — the front end maps both to 503 + Retry-After, never a silent
stale answer. A key the epoch does not carry raises
:class:`SegmentMiss` after registering the key on the reader's demand
stripe, so the next epoch carries it.

Serve cost: decoded payloads and shaped responses are memoized PER
SEGMENT GENERATION (the reader-side analogue of the store's versioned
``_cached_read``) — a polling dashboard's repeat query is one header
word compare + one dict hit.

Imported by reader processes: numpy + stdlib only, no jax.
"""

from __future__ import annotations

import pickle
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu import obs
from zipkin_tpu.internal.hex import epoch_minutes
from zipkin_tpu.obs import querytrace
from zipkin_tpu.ops import ttmerge
from zipkin_tpu.serving.segment import MirrorSegment, SegmentUnavailable

_MEMO_MAX = 256


class SegmentMiss(Exception):
    """The epoch does not carry this key; it has been demanded back to
    the publisher (503 + Retry-After — the next epoch carries it)."""

    def __init__(self, key: str, registered: bool) -> None:
        super().__init__(f"mirror key {key!r} not in the published epoch")
        self.key = key
        self.registered = registered


class StalenessExceeded(Exception):
    """The epoch is older than the request's bound (or the request
    demanded a fresh read, which a reader process cannot serve)."""

    def __init__(self, age_ms: float, bound_ms: float,
                 fresh_required: bool = False) -> None:
        super().__init__(
            f"epoch age {age_ms:.1f}ms exceeds bound {bound_ms:.1f}ms"
            if not fresh_required
            else "fresh read requested; readers serve published epochs only"
        )
        self.age_ms = age_ms
        self.bound_ms = bound_ms
        self.fresh_required = fresh_required


class _VocabView:
    """Read-only interner view rebuilt from the serialized name lists —
    the exact lookup/get semantics of `tpu/columnar.py` (id 0 = "",
    ``names`` excludes it, ``get`` knows only real ids)."""

    def __init__(self, services: List[str], span_names: List[str],
                 key_list) -> None:
        self.services = list(services)
        self.span_names = list(span_names)
        self.key_list = np.asarray(key_list, np.int32)
        self.svc_ids = {n: i for i, n in enumerate(self.services) if i}
        self.span_ids = {n: i for i, n in enumerate(self.span_names) if i}

    def svc_lookup(self, nid: int) -> str:
        return self.services[nid] if 0 <= nid < len(self.services) else ""

    def span_lookup(self, nid: int) -> str:
        return (
            self.span_names[nid] if 0 <= nid < len(self.span_names) else ""
        )


def quantile_rows(
    vv: _VocabView,
    qs: Sequence[float],
    source_q: np.ndarray,
    counts: np.ndarray,
    service_name: Optional[str],
    span_name: Optional[str],
) -> List[dict]:  # zt-reader-process: pure shaping over the decoded payload — replicates store._quantile_rows_inner byte-for-byte
    want_svc = vv.svc_ids.get(service_name.lower()) if service_name else None
    if service_name and want_svc is None:
        return []
    pairs = vv.key_list
    kids = np.arange(1, pairs.shape[0])
    mask = counts[kids] > 0
    if want_svc is not None:
        mask &= pairs[kids, 0] == want_svc
    if span_name:
        want_name = vv.span_ids.get(span_name.lower())
        if want_name is None:
            return []
        mask &= pairs[kids, 1] == want_name
    out = []
    for kid in kids[mask]:
        out.append(
            {
                "serviceName": vv.svc_lookup(int(pairs[kid, 0])),
                "spanName": vv.span_lookup(int(pairs[kid, 1])),
                "count": int(counts[kid]),
                "quantiles": {
                    float(q): float(source_q[kid, i])
                    for i, q in enumerate(qs)
                },
            }
        )
    return out


def cardinality_rows(
    vv: _VocabView, est: np.ndarray, global_row: int
) -> dict:  # zt-reader-process: pure shaping — replicates store._cardinality_rows output (envelope accounting is ingest-side)
    out = {"_global": float(est[global_row])}
    for name in vv.services[1:]:
        sid = vv.svc_ids.get(name)
        if sid:
            out[name] = float(est[sid])
    return out


def dependency_rows(
    vv: _VocabView, calls: np.ndarray, errs: np.ndarray
) -> List[dict]:  # zt-reader-process: pure shaping — store._tt_dependency_links + json_v2.link_to_dict, fused
    dense_c = np.asarray(calls)
    dense_e = np.asarray(errs)
    p_idx, c_idx = np.nonzero(dense_c)
    out: List[dict] = []
    for p, c in zip(p_idx, c_idx):
        parent = vv.svc_lookup(int(p))
        child = vv.svc_lookup(int(c))
        if not parent or not child:
            continue
        row = {
            "parent": parent,
            "child": child,
            "callCount": int(dense_c[p, c]),
        }
        if int(dense_e[p, c]):
            row["errorCount"] = int(dense_e[p, c])
        out.append(row)
    return out


def tt_epochs(end_ts: int, lookback: Optional[int], g: int) -> Tuple[int, int]:
    """Bucket-aligned epoch range — store._tt_epochs, replicated."""
    lb = lookback if lookback is not None else end_ts
    lo_ep = max(0, epoch_minutes(end_ts - lb) // g)
    hi_ep = max(0, epoch_minutes(end_ts) // g)
    return lo_ep, hi_ep


def _qkey(qs: Sequence[float]) -> str:
    return ",".join(f"{q:.6g}" for q in qs)


class SegmentView:
    """One reader's lock-free serving facade over the mirror segment.

    Not thread-safe across serves by design: one view per reader
    process (the front end is a single-threaded asyncio loop). All
    segment access is the seqlock read protocol — no lock, in any
    process, anywhere on the serve path (ZT13 proves it statically).
    """

    def __init__(self, segment: MirrorSegment, reader_idx: int = 0) -> None:
        self._seg = segment
        self.reader_idx = int(reader_idx)
        self._gen = -1
        self._p: Optional[dict] = None
        self._vv: Optional[_VocabView] = None
        self._memo: Dict[tuple, object] = {}
        # reader-local ledger (heartbeat words mirror the highlights)
        self.serves = 0
        self.misses = 0
        self.stale_rejects = 0
        self.fresh_rejects = 0
        self.unavailable = 0
        self.decodes = 0
        self.memo_hits = 0
        self.demand_requests = 0
        self.demand_overflow = 0
        self.errors = 0
        self.serve_age_ms = 0.0
        self.serve_age_max_ms = 0.0

    # -- epoch refresh -----------------------------------------------------

    def refresh(self) -> dict:  # zt-reader-process: seqlock frame read + unpickle; memoized per segment generation
        gen = self._seg.generation()
        if gen == self._gen and self._p is not None:
            return self._p
        frame = self._seg.read_frame()
        p = pickle.loads(frame.payload)
        self._vv = _VocabView(
            p["services"], p["span_names"], p["key_list"]
        )
        self._p = p
        self._gen = frame.gen
        self._memo.clear()
        self.decodes += 1
        return p

    # -- staleness / miss plumbing ----------------------------------------

    def _age_ms(self, p: dict) -> float:
        return max(0.0, (time.monotonic() - p["published_at"]) * 1000.0)

    def _check_bound(self, p: dict, staleness_ms: Optional[float],
                     default_ms: float) -> float:
        age = self._age_ms(p)
        if staleness_ms is not None and staleness_ms <= 0:
            self.fresh_rejects += 1
            raise StalenessExceeded(age, 0.0, fresh_required=True)
        bound = (
            float(staleness_ms) if staleness_ms is not None
            else float(default_ms)
        )
        if age > bound:
            self.stale_rejects += 1
            raise StalenessExceeded(age, bound)
        return age

    def _value(self, p: dict, key: str):
        val = p["values"].get(key)
        if val is None:
            self.demand_requests += 1
            registered = self._seg.demand_push(self.reader_idx, key)
            if not registered:
                self.demand_overflow += 1
            self.misses += 1
            self._beat()
            raise SegmentMiss(key, registered)
        return val

    def _k(self, tenant: Optional[str], base: str) -> str:
        return f"tenant:{tenant}:{base}" if tenant else base

    def _memoize(self, mkey: tuple, build):
        hit = self._memo.get(mkey)
        if hit is not None:
            self.memo_hits += 1
            return hit
        out = build()
        if len(self._memo) < _MEMO_MAX:
            self._memo[mkey] = out
        return out

    def _done(self, age_ms: float, t0: float, t0_ns: int) -> None:
        self.serves += 1
        self.serve_age_ms = age_ms
        if age_ms > self.serve_age_max_ms:
            self.serve_age_max_ms = age_ms
        self._beat()
        obs.record("reader_serve", time.perf_counter() - t0)
        querytrace.stamp_active(
            querytrace.QSEG_READER_SERVE, t0_ns, time.perf_counter_ns()
        )

    def _beat(self) -> None:
        self._seg.heartbeat(
            self.reader_idx,
            gen_seen=self._gen,
            serves=self.serves,
            age_us=int(self.serve_age_ms * 1000),
            demands=self.demand_requests,
            demand_overflow=self.demand_overflow,
            errors=self.errors,
        )

    # -- the four endpoints ------------------------------------------------

    def serve_dependencies(
        self, end_ts: int, lookback: int,
        staleness_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[List[dict], float]:  # zt-reader-process: route selection + shaping over the decoded epoch; no lock in any process
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        p = self.refresh()
        if p["tt_enabled"]:
            lo_ep, hi_ep = tt_epochs(
                end_ts, lookback, p["time_bucket_minutes"]
            )
            if lo_ep <= p["tt_sealed_through"]:
                key = self._k(tenant, f"ttq:{lo_ep}:{hi_ep}")
                ans = self._value(p, key)[1]
                age = self._check_bound(
                    p, staleness_ms, p["deps_max_stale_ms"]
                )
                rows = self._memoize(
                    ("deps", key),
                    lambda: dependency_rows(
                        self._vv, ans["calls"], ans["errs"]
                    ),
                )
                self._done(age, t0, t0_ns)
                return rows, age
        lo_min = epoch_minutes(end_ts - lookback)
        hi_min = epoch_minutes(end_ts)
        key = self._k(tenant, f"deps:{lo_min}:{hi_min}")
        val = self._value(p, key)
        age = self._check_bound(p, staleness_ms, p["deps_max_stale_ms"])
        rows = val[1]
        self._done(age, t0, t0_ns)
        return rows, age

    def serve_quantiles(
        self,
        qs: Sequence[float],
        service_name: Optional[str] = None,
        span_name: Optional[str] = None,
        use_digest: bool = True,
        end_ts: Optional[int] = None,
        lookback: Optional[int] = None,
        staleness_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[List[dict], float]:  # zt-reader-process: store.latency_quantiles route selection, replicated over the epoch
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        p = self.refresh()
        if end_ts is None and lookback is not None:
            end_ts = int(time.time() * 1000)
        qkey = _qkey(qs)
        qs = tuple(qs)
        if end_ts is not None:
            lo_ep, hi_ep = (
                tt_epochs(end_ts, lookback, p["time_bucket_minutes"])
                if p["tt_enabled"] else (0, -1)
            )
            if (
                use_digest and p["tt_enabled"]
                and lo_ep <= p["tt_sealed_through"]
            ):
                key = self._k(tenant, f"ttq:{lo_ep}:{hi_ep}")
                ans = self._value(p, key)[1]
                age = self._check_bound(p, staleness_ms, p["max_stale_ms"])
                rows = self._memoize(
                    ("quant", key, qs, service_name, span_name),
                    lambda: quantile_rows(
                        self._vv, qs,
                        ttmerge.digest_quantile(
                            np.asarray(ans["digest"]), qs
                        ),
                        ttmerge.digest_total(np.asarray(ans["digest"])),
                        service_name, span_name,
                    ),
                )
                self._done(age, t0, t0_ns)
                return rows, age
            lb = lookback if lookback is not None else end_ts
            lo_min = epoch_minutes(end_ts - lb)
            hi_min = epoch_minutes(end_ts)
            key = self._k(tenant, f"quant:w:{lo_min}:{hi_min}:{qkey}")
        else:
            src = "digest" if use_digest else "hist"
            key = self._k(tenant, f"quant:{src}:{qkey}")
        val = self._value(p, key)
        age = self._check_bound(p, staleness_ms, p["max_stale_ms"])
        source_q, counts = val[1], val[2]
        rows = self._memoize(
            ("quant", key, qs, service_name, span_name),
            lambda: quantile_rows(
                self._vv, qs, source_q, counts, service_name, span_name
            ),
        )
        self._done(age, t0, t0_ns)
        return rows, age

    def serve_cardinalities(
        self,
        staleness_ms: Optional[float] = None,
        end_ts: Optional[int] = None,
        lookback: Optional[int] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[dict, float]:  # zt-reader-process: store.trace_cardinalities route selection, replicated over the epoch
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        p = self.refresh()
        if end_ts is None and lookback is not None:
            end_ts = int(time.time() * 1000)
        if end_ts is not None and p["tt_enabled"]:
            lo_ep, hi_ep = tt_epochs(
                end_ts, lookback, p["time_bucket_minutes"]
            )
            key = self._k(tenant, f"ttq:{lo_ep}:{hi_ep}")
            ans = self._value(p, key)[1]
            age = self._check_bound(p, staleness_ms, p["max_stale_ms"])
            rows = self._memoize(
                ("card", key),
                lambda: cardinality_rows(
                    self._vv,
                    ttmerge.hll_estimate(np.asarray(ans["hll"])),
                    p["global_hll_row"],
                ),
            )
            self._done(age, t0, t0_ns)
            return rows, age
        key = self._k(tenant, "card")
        val = self._value(p, key)
        age = self._check_bound(p, staleness_ms, p["max_stale_ms"])
        est = val[1]
        rows = self._memoize(
            ("card", key),
            lambda: cardinality_rows(self._vv, est, p["global_hll_row"]),
        )
        self._done(age, t0, t0_ns)
        return rows, age

    def serve_overview(
        self,
        qs: Sequence[float],
        service_name: Optional[str] = None,
        span_name: Optional[str] = None,
        staleness_ms: Optional[float] = None,
        tenant: Optional[str] = None,
    ) -> Tuple[dict, float]:  # zt-reader-process: one-key overview serve; counters are the publish-instant snapshot, stamped as such
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        p = self.refresh()
        qs = tuple(qs)
        key = self._k(tenant, f"overview:{_qkey(qs)}")
        val = self._value(p, key)
        age = self._check_bound(p, staleness_ms, p["max_stale_ms"])
        source_q, counts, est = val[1], val[2], val[3]
        body = self._memoize(
            ("overview", key, qs, service_name, span_name),
            lambda: {
                "percentiles": quantile_rows(
                    self._vv, qs, source_q, counts,
                    service_name, span_name,
                ),
                "cardinalities": cardinality_rows(
                    self._vv, est, p["global_hll_row"]
                ),
                # the ingest_counters snapshot the publisher cut with
                # the epoch — consistent with the sketches above, not
                # with the ingest process's live counters
                "counters": p["counters"],
            },
        )
        self._done(age, t0, t0_ns)
        return body, age

    # -- observability -----------------------------------------------------

    def counters(self) -> Dict:
        """Flat gauges for the reader's ``/metrics`` (the mirror's
        counter-naming idiom, reader-prefixed)."""
        return {
            "readerIndex": self.reader_idx,
            "readerGeneration": self._gen,
            "readerSegmentGeneration": self._seg.generation(),
            "readerServes": self.serves,
            "readerMisses": self.misses,
            "readerStaleRejects": self.stale_rejects,
            "readerFreshRejects": self.fresh_rejects,
            "readerUnavailable": self.unavailable,
            "readerDecodes": self.decodes,
            "readerMemoHits": self.memo_hits,
            "readerDemandRequests": self.demand_requests,
            "readerDemandOverflow": self.demand_overflow,
            "readerErrors": self.errors,
            "readerServeAgeMs": round(self.serve_age_ms, 3),
            "readerServeAgeMaxMs": round(self.serve_age_max_ms, 3),
        }
