"""Reader-process supervisor: spawn, health-check, respawn, aggregate.

Spawns ``TPU_READERS`` reader processes (spawn context — a reader
import chain is numpy + stdlib + aiohttp, never jax), each on
``port_base + idx``, and keeps them alive: a dead child respawns on
the shared :class:`RespawnBackoff` schedule (`runtime/supervisor.py`),
and the cumulative respawn count lands in the segment's supervisor
header words so the INGEST process's ``/statusz`` serving block sees
it without any channel beyond the segment itself.

Health is read from the segment, not guessed: each serve updates the
reader's heartbeat stripe (pid, last generation seen, serve age), so
``status()`` reports per-reader generation lag against the segment's
live generation — a reader that stopped advancing is visibly lagging
before it is visibly dead.

Aggregation: :meth:`scrape_metrics` / :meth:`scrape_prometheus` fan
out to every live reader's HTTP surface and merge — prometheus lines
already carry their ``reader="rN"`` label from the reader itself, so
the merge is concatenation plus a supervisor self-block.
"""

from __future__ import annotations

import logging
import os
import time
import urllib.error
import urllib.request
from typing import Callable, Dict, List, Optional

from zipkin_tpu.runtime.supervisor import RespawnBackoff
from zipkin_tpu.serving.reader import run_reader
from zipkin_tpu.serving.segment import MirrorSegment

logger = logging.getLogger(__name__)

_SCRAPE_TIMEOUT_S = 2.0


class ReaderSupervisor:
    """Owns N reader children over one attached segment."""

    def __init__(
        self,
        segment: MirrorSegment,
        n_readers: int,
        port_base: int,
        *,
        target: Callable = run_reader,
        backoff: Optional[RespawnBackoff] = None,
    ) -> None:
        import multiprocessing as mp

        self._ctx = mp.get_context("spawn")
        self.segment = segment
        self.n_readers = int(n_readers)
        self.port_base = int(port_base)
        self._target = target
        self._backoff = backoff or RespawnBackoff()
        self._children: Dict[int, object] = {}
        self._spawned_at: Dict[int, float] = {}
        self.respawns = 0
        self.started = False

    def _spawn(self, idx: int):
        proc = self._ctx.Process(
            target=self._target,
            args=(self.segment.params(), idx, self.port_base + idx),
            name=f"zt-reader-r{idx}",
            daemon=True,
        )
        proc.start()
        self._children[idx] = proc
        self._spawned_at[idx] = time.monotonic()
        self._backoff.note_spawn(idx)
        return proc

    def start(self) -> None:
        if self.started:
            raise RuntimeError("reader supervisor already started")
        self.started = True
        self.segment.note_supervisor(os.getpid(), self.respawns)
        for idx in range(self.n_readers):
            self._spawn(idx)
        logger.info(
            "reader supervisor: %d readers on ports %d..%d",
            self.n_readers, self.port_base,
            self.port_base + self.n_readers - 1,
        )

    def poll(self) -> int:
        """One supervision pass: respawn dead children whose backoff
        window has passed. Returns how many respawned (the chaos test's
        observable)."""
        respawned = 0
        for idx, proc in list(self._children.items()):
            if proc is not None and proc.is_alive():
                continue
            if proc is not None:
                # newly observed death: record it once, then wait out
                # the backoff window before the respawn below
                proc.join(timeout=0)
                uptime = time.monotonic() - self._spawned_at.get(idx, 0.0)
                delay = self._backoff.note_death(idx, uptime)
                logger.warning(
                    "reader r%d died (exit %s, up %.1fs); respawning%s",
                    idx, proc.exitcode, uptime,
                    f" after {delay:.1f}s backoff" if delay else "",
                )
                self._children[idx] = None
            if self._backoff.ready(idx):
                self._spawn(idx)
                self.respawns += 1
                respawned += 1
                self.segment.note_supervisor(os.getpid(), self.respawns)
        return respawned

    def run(self, poll_s: float = 0.5,
            stop: Optional[Callable[[], bool]] = None) -> None:
        """Blocking supervision loop (the ``__main__`` driver)."""
        while stop is None or not stop():
            self.poll()
            time.sleep(poll_s)

    def stop(self, timeout_s: float = 5.0) -> None:
        for proc in self._children.values():
            if proc.is_alive():
                proc.terminate()
        for proc in self._children.values():
            proc.join(timeout=timeout_s)
            if proc.is_alive():  # pragma: no cover - stuck child
                proc.kill()
                proc.join(timeout=timeout_s)
        self._children.clear()

    # -- health / aggregation ---------------------------------------------

    def status(self) -> Dict:
        """The serving status block: segment header + per-reader
        heartbeats (generation lag, serve ages) + child liveness."""
        body = self.segment.status()
        alive = {
            idx: proc.is_alive() for idx, proc in self._children.items()
        }
        for row in body["readers"]:
            idx = int(row["reader"][1:])
            row["childAlive"] = alive.get(idx, False)
        body["respawns"] = self.respawns
        body["configuredReaders"] = self.n_readers
        body["portBase"] = self.port_base
        return body

    def _scrape(self, idx: int, path: str) -> Optional[str]:
        url = f"http://127.0.0.1:{self.port_base + idx}{path}"
        try:
            with urllib.request.urlopen(
                url, timeout=_SCRAPE_TIMEOUT_S
            ) as resp:
                return resp.read().decode("utf-8", "replace")
        except (urllib.error.URLError, OSError, TimeoutError):
            return None

    def scrape_metrics(self) -> Dict:
        """Per-reader ``/metrics`` JSON, reader-keyed, plus the
        supervisor's own block."""
        import json

        readers: Dict[str, object] = {}
        for idx in range(self.n_readers):
            raw = self._scrape(idx, "/metrics")
            if raw is None:
                readers[f"r{idx}"] = {"unreachable": True}
                continue
            try:
                readers[f"r{idx}"] = json.loads(raw).get("reader", {})
            except ValueError:
                readers[f"r{idx}"] = {"unreachable": True}
        return {
            "supervisor": {
                "pid": os.getpid(),
                "respawns": self.respawns,
                "configuredReaders": self.n_readers,
            },
            "readers": readers,
        }

    def scrape_prometheus(self) -> str:
        """Concatenated reader families (each line already labeled
        ``reader="rN"`` at the source) + supervisor gauges."""
        parts: List[str] = [
            f"zipkin_tpu_reader_supervisor_respawns {self.respawns}",
            f"zipkin_tpu_reader_supervisor_readers {self.n_readers}",
        ]
        for idx in range(self.n_readers):
            raw = self._scrape(idx, "/prometheus")
            if raw is None:
                parts.append(
                    f'zipkin_tpu_reader_up{{reader="r{idx}"}} 0'
                )
                continue
            parts.append(f'zipkin_tpu_reader_up{{reader="r{idx}"}} 1')
            parts.append(raw.rstrip("\n"))
        return "\n".join(parts) + "\n"
