"""L1/L2: the storage SPI, the in-memory oracle, and the TPU-backed store."""

from zipkin_tpu.storage.spi import (  # noqa: F401
    AutocompleteTags,
    QueryRequest,
    ServiceAndSpanNames,
    SpanConsumer,
    SpanStore,
    StorageComponent,
    Traces,
)
