"""The exact in-memory storage oracle.

Reference semantics: ``zipkin2/storage/InMemoryStorage.java`` (SURVEY.md
§2.1) — the parity oracle every other backend (including the TPU store) is
tested against. Bounded by ``max_span_count``: when exceeded, whole traces
are evicted oldest-first. Dependency links are computed online through
:class:`~zipkin_tpu.internal.dependency_linker.DependencyLinker` (§3.5).

Ordering contract: ``get_traces_query`` returns traces ordered by their most
recent span activity, newest first, with ``limit`` applied after filtering.
Duplicate span reports are merged at read time (``Trace.merge`` semantics).
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, List, Sequence, Set, Tuple

from zipkin_tpu.internal.dependency_linker import DependencyLinker
from zipkin_tpu.internal.span_node import merge_trace
from zipkin_tpu.model.span import DependencyLink, Span
from zipkin_tpu.storage.spi import (
    AutocompleteTags,
    QueryRequest,
    ServiceAndSpanNames,
    SpanConsumer,
    SpanStore,
    StorageComponent,
    trace_id_key,
)
from zipkin_tpu.utils.call import Call
from zipkin_tpu.utils.component import CheckResult


class InMemoryStorage(
    StorageComponent, SpanConsumer, SpanStore, ServiceAndSpanNames, AutocompleteTags
):
    def __init__(
        self,
        *,
        max_span_count: int = 500_000,
        strict_trace_id: bool = True,
        search_enabled: bool = True,
        autocomplete_keys: Sequence[str] = (),
    ) -> None:
        self.max_span_count = max_span_count
        self.strict_trace_id = strict_trace_id
        self.search_enabled = search_enabled
        self.autocomplete_keys = tuple(autocomplete_keys)
        self._lock = threading.Lock()
        self._spans_by_trace: Dict[str, List[Span]] = {}
        self._age_heap: List[Tuple[int, str]] = []
        self._min_ts: Dict[str, int] = {}
        self._span_count = 0
        self._closed = False

    # -- factories ---------------------------------------------------------

    def span_consumer(self) -> SpanConsumer:
        return self

    def span_store(self) -> SpanStore:
        return self

    def service_and_span_names(self) -> ServiceAndSpanNames:
        return self

    def autocomplete_tags(self) -> AutocompleteTags:
        return self

    def check(self) -> CheckResult:
        if self._closed:
            return CheckResult.failed(RuntimeError("closed"))
        return CheckResult.OK  # type: ignore[attr-defined]

    def close(self) -> None:
        self._closed = True

    def clear(self) -> None:
        with self._lock:
            self._spans_by_trace.clear()
            self._age_heap.clear()
            self._min_ts.clear()
            self._span_count = 0

    # -- write path --------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> Call[None]:
        def run() -> None:
            with self._lock:
                for span in spans:
                    key = trace_id_key(span.trace_id, self.strict_trace_id)
                    ts = span.timestamp_as_long()
                    bucket = self._spans_by_trace.get(key)
                    if bucket is None:
                        bucket = self._spans_by_trace[key] = []
                    # Eviction key is the trace's MIN span timestamp,
                    # updated continuously: the reference indexes every
                    # accepted span as a (timestamp, traceId) pair, so a
                    # late span with an earlier timestamp makes its trace
                    # MORE evictable. Stale heap entries are skipped
                    # lazily on pop.
                    cur = self._min_ts.get(key)
                    if cur is None or ts < cur:
                        self._min_ts[key] = ts
                        heapq.heappush(self._age_heap, (ts, key))
                    bucket.append(span)
                    self._span_count += 1
                self._evict_locked()

        return Call.of(run)

    # zt-lint: disable=ZT04 — the _locked suffix is the contract: the
    # sole caller (accept's run closure) already holds self._lock
    def _evict_locked(self) -> None:
        """Drop whole traces, oldest first, until under the bound.

        Amortized O(evicted log T): entries for already-evicted traces or
        superseded (stale) timestamps are skipped lazily.
        """
        while self._span_count > self.max_span_count and self._age_heap:
            ts, key = heapq.heappop(self._age_heap)
            if self._min_ts.get(key) != ts:
                continue  # stale entry: trace gone or re-keyed older
            spans = self._spans_by_trace.pop(key, None)
            del self._min_ts[key]
            if spans is not None:
                self._span_count -= len(spans)

    # -- read path ---------------------------------------------------------

    def get_trace(self, trace_id: str) -> Call[List[Span]]:
        def run() -> List[Span]:
            with self._lock:
                key = trace_id_key(trace_id, self.strict_trace_id)
                result = list(self._spans_by_trace.get(key, ()))
            return merge_trace(result)

        return Call.of(run)

    def get_traces(self, trace_ids: Sequence[str]) -> Call[List[List[Span]]]:
        def run() -> List[List[Span]]:
            out: List[List[Span]] = []
            with self._lock:
                seen: Set[str] = set()
                for trace_id in trace_ids:
                    key = trace_id_key(trace_id, self.strict_trace_id)
                    if key in seen:
                        continue
                    seen.add(key)
                    spans = self._spans_by_trace.get(key)
                    if spans:
                        out.append(merge_trace(spans))
            return out

        return Call.of(run)

    def get_traces_query(self, request: QueryRequest) -> Call[List[List[Span]]]:
        def run() -> List[List[Span]]:
            if not self.search_enabled:
                return []
            with self._lock:
                traces = [list(v) for v in self._spans_by_trace.values()]
            traces.sort(key=_trace_ts, reverse=True)
            out: List[List[Span]] = []
            for spans in traces:
                merged = merge_trace(spans)
                if request.test(merged):
                    out.append(merged)
                    if len(out) >= request.limit:
                        break
            return out

        return Call.of(run)

    def get_dependencies(self, end_ts: int, lookback: int) -> Call[List[DependencyLink]]:
        def run() -> List[DependencyLink]:
            window = QueryRequest(end_ts=end_ts, lookback=lookback, limit=2**31 - 1)
            linker = DependencyLinker()
            with self._lock:
                traces = [list(v) for v in self._spans_by_trace.values()]
            for spans in traces:
                merged = merge_trace(spans)
                if _in_window(merged, window):
                    linker.put_trace(merged)
            return linker.link()

        return Call.of(run)

    # -- names -------------------------------------------------------------

    def get_service_names(self) -> Call[List[str]]:
        def run() -> List[str]:
            if not self.search_enabled:
                return []
            names: Set[str] = set()
            with self._lock:
                for spans in self._spans_by_trace.values():
                    for s in spans:
                        if s.local_service_name:
                            names.add(s.local_service_name)
            return sorted(names)

        return Call.of(run)

    def get_remote_service_names(self, service_name: str) -> Call[List[str]]:
        def run() -> List[str]:
            if not self.search_enabled or not service_name:
                return []
            want = service_name.lower()
            names: Set[str] = set()
            with self._lock:
                for spans in self._spans_by_trace.values():
                    for s in spans:
                        if s.local_service_name == want and s.remote_service_name:
                            names.add(s.remote_service_name)
            return sorted(names)

        return Call.of(run)

    def get_span_names(self, service_name: str) -> Call[List[str]]:
        def run() -> List[str]:
            if not self.search_enabled or not service_name:
                return []
            want = service_name.lower()
            names: Set[str] = set()
            with self._lock:
                for spans in self._spans_by_trace.values():
                    for s in spans:
                        if s.local_service_name == want and s.name:
                            names.add(s.name)
            return sorted(names)

        return Call.of(run)

    # -- autocomplete ------------------------------------------------------

    def get_keys(self) -> Call[List[str]]:
        return Call.constant(list(self.autocomplete_keys))

    def get_values(self, key: str) -> Call[List[str]]:
        def run() -> List[str]:
            if key not in self.autocomplete_keys:
                return []
            values: Set[str] = set()
            with self._lock:
                for spans in self._spans_by_trace.values():
                    for s in spans:
                        v = s.tags.get(key)
                        if v:
                            values.add(v)
            return sorted(values)

        return Call.of(run)

    # -- introspection -----------------------------------------------------

    @property
    def span_count(self) -> int:
        return self._span_count

    def get_all_traces(self) -> List[List[Span]]:
        with self._lock:
            return [merge_trace(v) for v in self._spans_by_trace.values()]


def _trace_ts(spans: Sequence[Span]) -> int:
    """A trace's recency: its max span timestamp (0 when none)."""
    return max((s.timestamp_as_long() for s in spans), default=0)


def _in_window(spans: Sequence[Span], request: QueryRequest) -> bool:
    ts = 0
    for span in spans:
        if span.timestamp is not None:
            ts = span.timestamp if ts == 0 else min(ts, span.timestamp)
    return ts != 0 and request.min_ts <= ts <= request.max_ts
