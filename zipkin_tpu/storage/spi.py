"""The storage SPI: the seam between collectors/server and any backend.

Reference semantics: ``zipkin2/storage/StorageComponent.java``,
``SpanConsumer.java``, ``SpanStore.java``, ``Traces.java``,
``ServiceAndSpanNames.java``, ``AutocompleteTags.java``,
``QueryRequest.java`` and the result-shaping helpers ``StrictTraceId`` /
``GroupByTraceId`` (SURVEY.md §2.3). Every read/write returns a lazy
:class:`~zipkin_tpu.utils.call.Call` so backends may defer I/O, the throttle
can wrap them, and callers can retry via ``clone()``.

Key semantic: ``strict_trace_id=False`` makes 128-bit and 64-bit renditions
of the same trace id match on the low 64 bits — needed during instrumentation
migrations. Backends index by low-64 and post-filter when strict.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Mapping, Optional, Sequence

from zipkin_tpu.internal.hex import lower_64, normalize_trace_id
from zipkin_tpu.model.span import DependencyLink, Span
from zipkin_tpu.utils.call import Call
from zipkin_tpu.utils.component import Component


@dataclasses.dataclass(frozen=True)
class QueryRequest:
    """Trace search criteria, with the oracle predicate :meth:`test`.

    Times are epoch **milliseconds** (``end_ts``/``lookback``), durations
    **microseconds** — the same split the reference uses.
    """

    end_ts: int
    lookback: int
    limit: int = 10
    service_name: Optional[str] = None
    remote_service_name: Optional[str] = None
    span_name: Optional[str] = None
    annotation_query: Mapping[str, str] = dataclasses.field(default_factory=dict)
    min_duration: Optional[int] = None
    max_duration: Optional[int] = None

    def __post_init__(self) -> None:
        if self.end_ts <= 0:
            raise ValueError("endTs must be positive")
        if self.lookback <= 0:
            raise ValueError("lookback must be positive")
        if self.limit <= 0:
            raise ValueError("limit must be positive")
        if self.max_duration is not None:
            if self.min_duration is None:
                raise ValueError("minDuration is required when specifying maxDuration")
            if self.max_duration < self.min_duration:
                raise ValueError("maxDuration must be >= minDuration")
        if self.min_duration is not None and self.min_duration <= 0:
            raise ValueError("minDuration must be positive")
        # normalize names like the reference builder does
        for field in ("service_name", "remote_service_name", "span_name"):
            value = getattr(self, field)
            if value is not None:
                lowered = value.lower()
                if lowered in ("", "all"):
                    lowered = None
                object.__setattr__(self, field, lowered)

    @property
    def min_ts(self) -> int:  # epoch µs
        return (self.end_ts - self.lookback) * 1000

    @property
    def max_ts(self) -> int:  # epoch µs
        return self.end_ts * 1000

    def test(self, spans: Sequence[Span]) -> bool:
        """The oracle predicate: would this trace match the query?

        Mirrors ``QueryRequest#test``: the trace's first timestamp must land
        in the window; ``service_name`` constrains which spans may satisfy
        the other criteria; annotation/tag entries must all be found (on
        spans of the constrained service); duration bounds must hold on one
        such span.
        """
        ts = 0
        for span in spans:
            if span.timestamp is not None:
                ts = span.timestamp if ts == 0 else min(ts, span.timestamp)
        if ts == 0 or not (self.min_ts <= ts <= self.max_ts):
            return False

        service_unmatched = self.service_name
        remote_unmatched = self.remote_service_name
        span_name_unmatched = self.span_name
        ann_remaining: Dict[str, str] = dict(self.annotation_query)
        duration_ok = self.min_duration is None

        for span in spans:
            local = span.local_service_name
            if self.service_name is None or self.service_name == local:
                for a in span.annotations:
                    if a.value in ann_remaining and ann_remaining[a.value] == "":
                        del ann_remaining[a.value]
                for k, v in span.tags.items():
                    want = ann_remaining.get(k)
                    if want is not None and (want == "" or want == v):
                        del ann_remaining[k]
                if remote_unmatched is not None and remote_unmatched == span.remote_service_name:
                    remote_unmatched = None
                if span_name_unmatched is not None and span_name_unmatched == span.name:
                    span_name_unmatched = None
                if not duration_ok and span.duration is not None:
                    if self.max_duration is not None:
                        duration_ok = (
                            self.min_duration <= span.duration <= self.max_duration
                        )
                    else:
                        duration_ok = span.duration >= self.min_duration
            if service_unmatched is not None and service_unmatched == local:
                service_unmatched = None
        return (
            service_unmatched is None
            and remote_unmatched is None
            and span_name_unmatched is None
            and not ann_remaining
            and duration_ok
        )


class SpanConsumer:
    """The write path: ``accept`` returns a Call that persists the spans."""

    def accept(self, spans: Sequence[Span]) -> Call[None]:
        raise NotImplementedError


class Traces:
    def get_trace(self, trace_id: str) -> Call[List[Span]]:
        raise NotImplementedError

    def get_traces(self, trace_ids: Sequence[str]) -> Call[List[List[Span]]]:
        raise NotImplementedError


class SpanStore(Traces):
    """The read path."""

    def get_traces_query(self, request: QueryRequest) -> Call[List[List[Span]]]:
        raise NotImplementedError

    def get_dependencies(self, end_ts: int, lookback: int) -> Call[List[DependencyLink]]:
        raise NotImplementedError


class ServiceAndSpanNames:
    def get_service_names(self) -> Call[List[str]]:
        raise NotImplementedError

    def get_remote_service_names(self, service_name: str) -> Call[List[str]]:
        raise NotImplementedError

    def get_span_names(self, service_name: str) -> Call[List[str]]:
        raise NotImplementedError


class AutocompleteTags:
    def get_keys(self) -> Call[List[str]]:
        raise NotImplementedError

    def get_values(self, key: str) -> Call[List[str]]:
        raise NotImplementedError


class StorageComponent(Component):
    """Factory for the split read/write interfaces over one backend."""

    strict_trace_id: bool = True
    search_enabled: bool = True
    autocomplete_keys: Sequence[str] = ()

    def span_consumer(self) -> SpanConsumer:
        raise NotImplementedError

    def span_store(self) -> SpanStore:
        raise NotImplementedError

    def traces(self) -> Traces:
        return self.span_store()

    def service_and_span_names(self) -> ServiceAndSpanNames:
        raise NotImplementedError

    def autocomplete_tags(self) -> AutocompleteTags:
        raise NotImplementedError


# -- result shaping shared by backends ------------------------------------


def trace_id_key(trace_id: str, strict: bool) -> str:
    """The grouping key for a trace id under (non-)strict matching."""
    normalized = normalize_trace_id(trace_id)
    return normalized if strict else format(lower_64(normalized), "016x")


def group_by_trace_id(spans: Sequence[Span], strict: bool) -> List[List[Span]]:
    """Bucket spans into traces, optionally collapsing on low-64 bits.

    Reference: ``zipkin2/storage/GroupByTraceId.java``.
    """
    grouped: Dict[str, List[Span]] = {}
    for span in spans:
        grouped.setdefault(trace_id_key(span.trace_id, strict), []).append(span)
    return list(grouped.values())


def strict_filter(traces: List[List[Span]], trace_id: str) -> List[List[Span]]:
    """Post-filter groups to exact trace-id matches (strict mode helper).

    Reference: ``zipkin2/storage/StrictTraceId.java``.
    """
    want = normalize_trace_id(trace_id)
    return [t for t in traces if t and t[0].trace_id == want]
