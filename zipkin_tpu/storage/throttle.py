"""Bounded-concurrency storage wrapper — the backpressure mechanism.

Reference semantics: ``zipkin-server/.../internal/throttle/
ThrottledStorageComponent.java`` and ``ThrottledCall.java`` (SURVEY.md §2.4,
§5): wrap every storage call in a semaphore with a bounded wait queue; when
the queue is full the call is rejected immediately (shed load) rather than
piling up until the process dies. The collector counts the rejection as
dropped spans and the transport backs off.
"""

from __future__ import annotations

import threading
from typing import List, Sequence

from zipkin_tpu.model.span import DependencyLink, Span
from zipkin_tpu.storage.spi import (
    AutocompleteTags,
    QueryRequest,
    ServiceAndSpanNames,
    SpanConsumer,
    SpanStore,
    StorageComponent,
)
from zipkin_tpu.utils.call import Call
from zipkin_tpu.utils.component import CheckResult


class RejectedExecutionError(RuntimeError):
    """The throttle's wait queue is full; shed the work."""


class _Throttle:
    def __init__(self, max_concurrency: int, max_queue: int) -> None:
        self._semaphore = threading.BoundedSemaphore(max_concurrency)
        self._queue_slots = threading.BoundedSemaphore(max(max_queue, 1))
        # overload signal for the sampling tier: when armed (see
        # ThrottledStorage.set_pressure_delegate), every rejection also
        # tells the rate controller to tighten per-service keep rates —
        # degradation order is "sample harder" BEFORE "shed at the door"
        self.on_reject = None

    def run(self, fn):
        if not self._queue_slots.acquire(blocking=False):
            cb = self.on_reject
            if cb is not None:
                try:
                    cb()
                except Exception:  # a signal, never a second failure
                    pass
            raise RejectedExecutionError("storage throttle queue is full")
        try:
            with self._semaphore:
                return fn()
        finally:
            self._queue_slots.release()


class _ThrottledCall(Call):
    def __init__(self, delegate: Call, throttle: _Throttle) -> None:
        super().__init__()
        self._delegate = delegate
        self._throttle = throttle

    def _do_execute(self):
        return self._throttle.run(self._delegate.execute)

    def _clone_impl(self) -> "Call":
        return _ThrottledCall(self._delegate.clone(), self._throttle)


class ThrottledStorage(StorageComponent):
    """Delegates everything, wrapping calls in the shared throttle."""

    def __init__(
        self,
        delegate: StorageComponent,
        *,
        max_concurrency: int = 8,
        max_queue: int = 100,
    ) -> None:
        self.delegate = delegate
        self.strict_trace_id = delegate.strict_trace_id
        self.search_enabled = delegate.search_enabled
        self.autocomplete_keys = delegate.autocomplete_keys
        self._throttle = _Throttle(max_concurrency, max_queue)
        # auto-wire the overload signal when the wrapped storage carries a
        # rate controller (TPU tier with TPU_SAMPLING_BUDGET set)
        controller = getattr(delegate, "sampling_controller", None)
        if controller is not None:
            self.set_pressure_delegate(controller.note_pressure)

    def set_pressure_delegate(self, callback) -> None:
        """Arm ``callback`` to fire on every throttle rejection (the
        sampling tier's RateController.note_pressure). Pass ``None`` to
        disarm."""
        self._throttle.on_reject = callback

    def _wrap(self, call: Call) -> Call:
        return _ThrottledCall(call, self._throttle)

    def __getattr__(self, name: str):
        # Forward non-SPI extensions (e.g. the TPU tier's latency_quantiles /
        # trace_cardinalities / ingest_counters / snapshot) so wrapping a
        # storage in the throttle doesn't hide its extra read surface.
        if name == "delegate":  # not yet set during __init__
            raise AttributeError(name)
        attr = getattr(self.delegate, name)
        if name == "ingest_json_fast":
            # The collector probes hasattr(storage, "ingest_json_fast") and
            # then bypasses span_consumer() — the fast hot path must still
            # pay the limiter or TPU_FAST_INGEST + STORAGE_THROTTLE_ENABLED
            # silently disables backpressure.
            throttle = self._throttle

            def _throttled_fast(*args, **kwargs):
                return throttle.run(lambda: attr(*args, **kwargs))

            return _throttled_fast
        return attr

    def span_consumer(self) -> SpanConsumer:
        inner = self.delegate.span_consumer()
        outer = self

        class _Consumer(SpanConsumer):
            def accept(self, spans: Sequence[Span]) -> Call[None]:
                return outer._wrap(inner.accept(spans))

        return _Consumer()

    def span_store(self) -> SpanStore:
        inner = self.delegate.span_store()
        outer = self

        class _Store(SpanStore):
            def get_trace(self, trace_id: str) -> Call[List[Span]]:
                return outer._wrap(inner.get_trace(trace_id))

            def get_traces(self, trace_ids) -> Call[List[List[Span]]]:
                return outer._wrap(inner.get_traces(trace_ids))

            def get_traces_query(self, request: QueryRequest) -> Call[List[List[Span]]]:
                return outer._wrap(inner.get_traces_query(request))

            def get_dependencies(
                self, end_ts: int, lookback: int, **kwargs
            ) -> Call[List[DependencyLink]]:
                # kwargs carries non-SPI extensions (the TPU tier's
                # per-request staleness_ms mirror bound); the server only
                # passes them when the delegate supports the mirror
                return outer._wrap(
                    inner.get_dependencies(end_ts, lookback, **kwargs)
                )

        return _Store()

    def traces(self):
        return self.span_store()

    def service_and_span_names(self) -> ServiceAndSpanNames:
        inner = self.delegate.service_and_span_names()
        outer = self

        class _Names(ServiceAndSpanNames):
            def get_service_names(self):
                return outer._wrap(inner.get_service_names())

            def get_remote_service_names(self, service_name: str):
                return outer._wrap(inner.get_remote_service_names(service_name))

            def get_span_names(self, service_name: str):
                return outer._wrap(inner.get_span_names(service_name))

        return _Names()

    def autocomplete_tags(self) -> AutocompleteTags:
        inner = self.delegate.autocomplete_tags()
        outer = self

        class _Tags(AutocompleteTags):
            def get_keys(self):
                return outer._wrap(inner.get_keys())

            def get_values(self, key: str):
                return outer._wrap(inner.get_values(key))

        return _Tags()

    def check(self) -> CheckResult:
        return self.delegate.check()

    def close(self) -> None:
        self.delegate.close()
