"""STORAGE_TYPE=tpu — the autoconfig-facing adapter over the device tier.

Mirrors the per-backend autoconfig pattern of the reference server
(``zipkin-server/.../internal/{cassandra3,elasticsearch,...}``, SURVEY.md
§2.4): this module maps flat server config knobs onto the core
:class:`zipkin_tpu.tpu.store.TpuStorage` construction (mesh selection,
archive bound, checkpoint wiring).
"""

from __future__ import annotations

import logging
from typing import Optional, Sequence

from zipkin_tpu.obs import querytrace
from zipkin_tpu.tpu.state import AggConfig
from zipkin_tpu.tpu.store import TpuStorage as _CoreTpuStorage

logger = logging.getLogger(__name__)


class TpuStorage(_CoreTpuStorage):
    def __init__(
        self,
        *,
        max_span_count: int = 500_000,
        batch_size: int = 8192,
        num_devices: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        config: Optional[AggConfig] = None,
        strict_trace_id: bool = True,
        search_enabled: bool = True,
        autocomplete_keys: Sequence[str] = (),
        fast_archive_sample: int = 64,
        wal_dir: Optional[str] = None,
        wal_fsync: bool = False,
        archive_dir: Optional[str] = None,
        archive_max_bytes: int = 2 << 30,
        archive_segment_bytes: int = 64 << 20,
        sampling_budget: float = 0.0,
        sampling_interval_s: float = 5.0,
        sampling_min_rate: int = 256,
        sampling_tail_quantile: float = 0.99,
        sampling_rare_min: Optional[int] = None,
        snapshot_keep: int = 2,
        scrub_interval_s: float = 0.0,
        scrub_bytes_per_sec: int = 8 << 20,
        mirror_segment_bytes: int = 0,
        mirror_segment_readers: int = 4,
    ) -> None:
        mesh = None
        if num_devices is not None:
            from zipkin_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(num_devices)
        super().__init__(
            config=config,
            mesh=mesh,
            strict_trace_id=strict_trace_id,
            search_enabled=search_enabled,
            autocomplete_keys=autocomplete_keys,
            archive_max_span_count=max_span_count,
            pad_to_multiple=min(batch_size, 1024),
            fast_archive_sample=fast_archive_sample,
            archive_dir=archive_dir,
            archive_max_bytes=archive_max_bytes,
            archive_segment_bytes=archive_segment_bytes,
            sampling_budget=sampling_budget,
            sampling_interval_s=sampling_interval_s,
            sampling_min_rate=sampling_min_rate,
            sampling_tail_quantile=sampling_tail_quantile,
            sampling_rare_min=sampling_rare_min,
        )
        import threading
        import time

        self.batch_size = batch_size
        self.checkpoint_dir = checkpoint_dir
        # fallback depth: snapshot commits retain this many intact
        # generations; the WAL keeps the suffix back to the oldest one
        # (tpu/snapshot.py, ISSUE 7)
        self.snapshot_keep = max(1, int(snapshot_keep))
        self._snapshot_lock = threading.Lock()
        # durability-lag gauge: age of the last persisted generation
        # (boot counts as the epoch until the first snapshot lands)
        self._last_snapshot_mono = time.monotonic()
        # disk-exhaustion degraded mode (ISSUE 13): an ENOSPC snapshot
        # save is dropped (prior generations stay intact) and retried on
        # the next cycle; the flag feeds the durability_at_risk SLO page
        self._snapshot_at_risk = False
        self._snapshot_enospc = 0
        # boot restore/replay must not re-gate: WAL batches were compacted
        # to kept lanes at log time and replay restores the exact sampler
        # counters from record meta — a second verdict pass would re-drop
        # (or double-count) spans. Disarm the device-plane gate for the
        # whole resume sequence; install_sampler() re-arms it below.
        self.agg.sampler = None
        restored = False
        if checkpoint_dir:
            from zipkin_tpu.tpu.snapshot import maybe_restore

            t0 = time.perf_counter()
            restored = maybe_restore(self, checkpoint_dir)
            self.restore_stats["restoreMs"] = round(
                (time.perf_counter() - t0) * 1000.0, 3
            )
        if wal_dir:
            # boot order matters: restore the snapshot first (sets
            # agg.wal_seq to its cutoff), replay the WAL tail the
            # snapshot missed, THEN attach the hook so new batches log
            # with delta cursors at the post-replay vocab state
            from zipkin_tpu.tpu import wal as wal_mod

            # fsync=False bounds durability at process crash (acked
            # batches sit in the OS page cache until the kernel flushes);
            # TPU_WAL_FSYNC=true extends it to host/power failure at a
            # per-append fsync cost — see ARCHITECTURE.md "durability
            # plane" for the boundary statement
            wal = wal_mod.WriteAheadLog(wal_dir, fsync=wal_fsync)
            t0 = time.perf_counter()
            # contention-ledger attribution: boot replay holds the
            # aggregator lock for whole batches; name it so a post-boot
            # ledger read doesn't show a giant "unattributed" hold
            with querytrace.lock_label("wal_replay"):
                applied = wal_mod.replay(
                    self, wal, from_seq=self.agg.wal_seq
                )
            self.restore_stats["walReplayBatches"] = applied
            self.restore_stats["walReplayMs"] = round(
                (time.perf_counter() - t0) * 1000.0, 3
            )
            wal_mod.attach(self, wal)
        if restored or self.restore_stats["walReplayBatches"]:
            import logging

            logging.getLogger(__name__).info(
                "boot resume: snapshot %s (%.1f ms), WAL replayed %d "
                "batches (%.1f ms); durable span count %d (transport "
                "offset resume point)",
                "restored" if restored else "absent",
                self.restore_stats["restoreMs"],
                self.restore_stats["walReplayBatches"],
                self.restore_stats["walReplayMs"],
                self.agg.host_counters.get("spans", 0),
            )
        # resume is complete: re-arm the sampling tier (publishes the
        # restored host tables to the device leaves, then reinstalls the
        # ingest-funnel gate) and only now start the rate controller so
        # its first tick sees post-replay tallies, not a replay burst
        self.install_sampler()
        if self.sampling_controller is not None:
            self.sampling_controller.start()
        # transports that track offsets (replay files, Kafka) resume
        # from the durable span count — the last leg of the boot-time
        # restore sequence (snapshot -> WAL replay -> transport offset)
        self.resume_offset = int(self.agg.host_counters.get("spans", 0))
        # scale-out read serving (serving/, ISSUE 19): create the shm
        # mirror segment BEFORE the boot publish below, so the very
        # first epoch — including a crash-resume's restored state —
        # lands in shared memory and reader processes attaching at any
        # point after boot serve it byte-identically to the in-process
        # mirror (tests/test_serving_parity.py).
        self.mirror_segment = None
        if mirror_segment_bytes > 0:
            from zipkin_tpu.serving.segment import MirrorSegment

            self.mirror_segment = MirrorSegment(
                readers=mirror_segment_readers,
                capacity=mirror_segment_bytes,
            )
            self.attach_mirror_segment(self.mirror_segment)
        # cut the first mirror epoch from the restored state BEFORE the
        # ticker exists: the first post-boot dashboard read serves
        # lock-free from a snapshot that already reflects the resumed
        # sketches (crash-resume contract, tests/test_read_mirror.py)
        self.publish_mirror()
        # the transfer ledger measures SERVING traffic (one pull per
        # query is the invariant); boot-time restore/replay pulls are
        # not queries, so the count starts clean here — the boot mirror
        # publish above happens first for the same reason
        self.agg.read_stats["host_transfers"] = 0
        # background at-rest CRC scrubber (ISSUE 7): re-verifies sealed
        # WAL segments, archive frames, and retained snapshot
        # generations on a paced cadence. Off unless an interval is
        # configured AND something durable exists to scrub.
        if scrub_interval_s > 0 and (
            checkpoint_dir or wal_dir or self._disk is not None
        ):
            from zipkin_tpu.runtime.scrub import Scrubber

            self.scrubber = Scrubber(
                self,
                interval_s=scrub_interval_s,
                bytes_per_sec=scrub_bytes_per_sec,
            )
            self.scrubber.start()

    def snapshot(self) -> Optional[str]:
        """Persist device sketch state (see tpu/snapshot.py); returns
        path. WAL segments fully covered by the OLDEST retained
        generation are deleted — truncating at the newest generation's
        wal_seq would delete exactly the suffix a digest-mismatch
        fallback needs to replay (ISSUE 7 coverage rule).
        Serialized: a cancelled periodic snapshot's worker thread may
        still be mid-save when a shutdown snapshot starts — unserialized,
        their independent state/meta renames could pair a newer state
        file with an older wal_seq, making the next boot double-replay."""
        if not self.checkpoint_dir:
            return None
        import time

        from zipkin_tpu import obs
        from zipkin_tpu.tpu.snapshot import retained_coverage, save

        with self._snapshot_lock:
            if self._closed:
                # an orphaned periodic-snapshot thread can reach here
                # after shutdown (its asyncio task was cancelled but the
                # worker thread kept running); close() holds this lock,
                # so the flag check is race-free
                return None
            t0 = time.perf_counter()
            # ledger attribution: the save holds the aggregator lock
            # while it reads device state out for persistence
            try:
                with querytrace.lock_label("snapshot"):
                    path = save(
                        self, self.checkpoint_dir, keep=self.snapshot_keep
                    )
            except OSError as e:
                import errno as _errno

                if e.errno != _errno.ENOSPC:
                    raise
                # degraded, not dead: the commit protocol renames only
                # after a complete write, so every retained generation
                # is still intact — flag at-risk (snapshotAgeS keeps
                # climbing into its SLO) and retry next cycle
                self._snapshot_enospc += 1
                if not self._snapshot_at_risk:
                    logger.error(
                        "snapshot save hit ENOSPC: durability AT RISK "
                        "(retained generations intact; retrying next "
                        "cycle)"
                    )
                self._snapshot_at_risk = True
                return None
            wal = getattr(self, "wal", None)
            if wal is not None:
                covered = retained_coverage(self.checkpoint_dir)
                if covered is not None:
                    wal.truncate_covered(covered)
                # full state just became durable: an ENOSPC-missed WAL
                # window no longer threatens acked spans
                wal.clear_at_risk()
            self._snapshot_at_risk = False
            obs.record("snapshot", time.perf_counter() - t0)
            self._last_snapshot_mono = time.monotonic()
        return path

    def ingest_counters(self) -> dict:
        counters = super().ingest_counters()
        if self.checkpoint_dir:
            import time

            counters["snapshotAgeS"] = round(
                time.monotonic() - self._last_snapshot_mono, 3
            )
        counters["snapshotEnospc"] = self._snapshot_enospc
        wal = getattr(self, "wal", None)
        if wal is not None:
            counters["walEnospc"] = wal.enospc_count
            counters["walMissedRecords"] = wal.missed_records
        # the durability_at_risk SLO page keys off this single gauge:
        # 1 whenever ANY durable tier is in ENOSPC-degraded mode
        # (archive at-risk is excluded — a lossy cache dropping batches
        # is degraded service, not an acked-durability breach)
        counters["durabilityAtRisk"] = int(
            self._snapshot_at_risk
            or (wal is not None and wal.at_risk)
        )
        return counters

    def close(self) -> None:
        # an attached MP fan-out tier (server sets .mp_ingester) must be
        # drained + torn down BEFORE the WAL detaches: its dispatcher
        # feeds ingest_fused, whose wal_hook is the durability seam —
        # closing the segment under live dispatch would strand 202-acked
        # spans. The server's stop() normally does this (and close() is
        # idempotent); this is the belt for embedders/benches that only
        # call storage.close().
        ing = getattr(self, "mp_ingester", None)
        if ing is not None:
            try:
                if ing._dispatch_error is None and not ing._closed:
                    ing.drain()
            except Exception:
                logger.exception("mp-ingest drain failed during close")
            finally:
                ing.close()
                self.mp_ingester = None
        # serialize with snapshot(): a snapshot mid-flight finishes
        # before teardown, and any later attempt sees _closed
        with self._snapshot_lock:
            wal = getattr(self, "wal", None)
            if wal is not None:
                # detach the hook before closing the segment, or a
                # reused aggregator could append to a closed file
                self.agg.wal_hook = None
                wal.close()
            seg = getattr(self, "mirror_segment", None)
            if seg is not None:
                # detach the sink first so a late ticker publish cannot
                # write through a closed shm mapping
                self.mirror.segment_sink = None
                seg.close()
                self.mirror_segment = None
            super().close()
