"""Test kit: an embedded mock Zipkin for instrumentation tests.

Reference semantics: ``zipkin-junit``'s ``ZipkinRule`` /
``zipkin-junit5``'s ``ZipkinExtension`` (SURVEY.md §2.6) — a real HTTP
endpoint that records what clients POST, can inject failures
(``HttpFailure.sendErrorResponse`` / ``disconnectDuringBody``), and
exposes stored traces + collector metrics for assertions.

Usage (sync facade over the aiohttp server, runs its own loop thread):

    with ZipkinMock() as zipkin:
        my_tracer.configure(endpoint=zipkin.http_url)
        ... exercise instrumented code ...
        assert zipkin.trace_count == 1
"""

from zipkin_tpu.testkit.mock import HttpFailure, ZipkinMock

__all__ = ["HttpFailure", "ZipkinMock"]
