"""The embedded mock server behind :class:`ZipkinMock`.

Implementation notes: a private event loop on a daemon thread runs the
same ``ZipkinServer`` app as production over in-memory storage, so mock
behavior can't drift from the real collector; failure injection wraps the
ingest route the way ``ZipkinRule`` enqueues ``HttpFailure``s ahead of
OkHttp's MockWebServer responses.
"""

from __future__ import annotations

import asyncio
import dataclasses
import threading
from collections import deque
from typing import Deque, List, Optional, Sequence

from aiohttp import web

from zipkin_tpu.model.span import Span
from zipkin_tpu.server.app import ZipkinServer
from zipkin_tpu.server.config import ServerConfig
from zipkin_tpu.storage.memory import InMemoryStorage


@dataclasses.dataclass(frozen=True)
class HttpFailure:
    """One enqueued ingest failure (consumed in FIFO order)."""

    status: int = 500
    body: str = "injected failure"
    disconnect: bool = False

    @staticmethod
    def send_error_response(status: int, body: str = "") -> "HttpFailure":
        return HttpFailure(status=status, body=body)

    @staticmethod
    def disconnect_during_body() -> "HttpFailure":
        return HttpFailure(disconnect=True)


class ZipkinMock:
    """Embedded mock zipkin; start()/close() or use as a context manager."""

    def __init__(self, port: int = 0) -> None:
        self.storage = InMemoryStorage()
        self._config = ServerConfig(host="127.0.0.1", port=port)
        self._failures: Deque[HttpFailure] = deque()
        self._request_count = 0
        self._server: Optional[ZipkinServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self.port: Optional[int] = None

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "ZipkinMock":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("mock zipkin failed to start")
        return self

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._start_async())
        self._started.set()
        self._loop.run_forever()

    async def _start_async(self) -> None:
        server = ZipkinServer(self._config, storage=self.storage)
        app = server.make_app()
        app.middlewares.append(self._failure_middleware)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", self._config.port)
        await site.start()
        self.port = runner.addresses[0][1]
        self._runner = runner
        self._server = server

    @web.middleware
    async def _failure_middleware(self, request: web.Request, handler):
        if request.method == "POST" and request.path.endswith("/spans"):
            self._request_count += 1
            if self._failures:
                failure = self._failures.popleft()
                if failure.disconnect:
                    await request.read()
                    request.transport.close()
                    raise web.HTTPInternalServerError()  # connection is gone
                return web.Response(status=failure.status, text=failure.body)
        return await handler(request)

    def close(self) -> None:
        if self._loop is not None:
            async def _stop():
                await self._runner.cleanup()

            fut = asyncio.run_coroutine_threadsafe(_stop(), self._loop)
            fut.result(timeout=5)
            self._loop.call_soon_threadsafe(self._loop.stop)
            self._thread.join(timeout=5)
            self._loop = None

    def __enter__(self) -> "ZipkinMock":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- assertions ------------------------------------------------------

    @property
    def http_url(self) -> str:
        return f"http://127.0.0.1:{self.port}/api/v2/spans"

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    @property
    def http_request_count(self) -> int:
        return self._request_count

    @property
    def trace_count(self) -> int:
        return len(self.storage.get_all_traces())

    def traces(self) -> List[List[Span]]:
        return self.storage.get_all_traces()

    def store_spans(self, spans: Sequence[Span]) -> None:
        """Seed spans directly (ZipkinRule#storeSpans)."""
        self.storage.accept(list(spans)).execute()

    def enqueue_failure(self, failure: HttpFailure) -> None:
        self._failures.append(failure)

    def collector_metrics(self):
        assert self._server is not None
        return self._server.metrics
