"""The TPU aggregation tier: columnar span batches, device sketch state,
the jit'd ingest step, and the storage SPI implementation backed by them.

This package is the "new thing" the rebuild adds over the reference
(BASELINE north star): a ``zipkin-storage-tpu`` equivalent where span
batches stream into JAX arrays and aggregates (latency digests, HLL
cardinalities, dependency links) are maintained on-device.
"""
