"""Disk-backed raw-span archive: the trace STORE behind the sketches.

The reference is a trace store first — every ingested span stays
queryable for the retention window (``zipkin2/storage/InMemoryStorage``
semantics; the row backends in SURVEY.md §2.3). The r3 rebuild's fast
mode kept only a 1-in-64 trace sample in RAM, so ``GET
/api/v2/trace/{id}`` returned nothing for 63 of 64 traces (VERDICT r3
order 2). This module closes that gap for the line-rate path:

- **Write path** (once per ingest batch, sequential IO): the raw JSON
  payload is appended to the current segment file inside a
  self-describing frame, together with per-span byte extents (the C
  parser records them — ``native/span_json.c``) and the columnar search
  fields (trace-id lanes, service/name/key ids, timestamp, duration,
  error). No re-encoding, no per-span work.
- **Segments** roll at a size bound and are SEALED with two sidecar
  ``.npy`` index files: span rows sorted by the span's low-64 trace id,
  plus that sorted id column. Sealed indexes are read back
  ``mmap_mode='r'`` — lookups touch pages, not RSS, so memory stays
  flat however much history is on disk.
- **Reads**: ``get_trace`` binary-searches each segment's sorted id
  column (newest first) and preads exactly the matching spans' byte
  extents; strict-trace-id mode verifies the full 128-bit id from the
  stored high lanes. ``get_traces`` scans segment columns newest-first
  with vectorized candidate masks (service/span-name/remote-service/
  duration bounds), then decodes candidate TRACES and applies the exact
  ``QueryRequest.test`` predicate — annotationQuery and any other
  non-indexed clause are exact by post-filtering, the same
  fetch-then-filter shape the reference's row backends use.
- **Retention** is a disk-byte budget (``max_bytes``): oldest segments
  are deleted whole, so the queryable window is "whatever the budget
  holds" — the bounded analog of the reference's TTL'd daily indexes.
- **Recovery**: frames carry a magic + CRC; an unsealed tail segment is
  rebuilt by scanning its frames on boot (a torn final frame is
  truncated, matching the WAL's torn-tail rule).

Columns per span (u32 lanes): tl0 tl1 th0 th1 | off len | svc<<16|rsvc
| name | key | ts_min | dur<<1|err. 44 B/span of index beside the raw
JSON bytes.
"""

from __future__ import annotations

import errno
import logging
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu import faults

logger = logging.getLogger(__name__)

_MAGIC = 0x5A415243  # "ZARC"
_FRAME = struct.Struct("<IIII")  # magic, n_spans, payload_len, payload_crc
COLS = 11  # u32 lanes per span (see module docstring)


def verify_frames(path: str) -> dict:
    """At-rest integrity scan of one segment's data file (the
    scrubber's archive leg): walk every frame re-checking magic,
    structure, and payload crc — the sealed sidecar indexes carry the
    byte extents but no digest, so this is the only thing that can see
    rot in the raw span bytes. Returns ``{"ok", "frames", "spans",
    "bytes", "bad_offset"}``; ``spans`` counts spans in GOOD frames."""
    out = dict(ok=True, frames=0, spans=0, bytes=0, bad_offset=None)
    with open(path, "rb") as fh:
        while True:
            off = fh.tell()
            hdr = fh.read(_FRAME.size)
            if not hdr:
                break
            bad = len(hdr) < _FRAME.size
            if not bad:
                magic, n, plen, crc = _FRAME.unpack(hdr)
                bad = magic != _MAGIC
            if not bad:
                need = n * COLS * 4 + plen
                body = fh.read(need)
                bad = len(body) < need or zlib.crc32(body[n * COLS * 4:]) != crc
            if bad:
                out["ok"] = False
                out["bad_offset"] = off
                break
            out["frames"] += 1
            out["spans"] += n
            out["bytes"] = fh.tell()
    return out


def _id64(tl0: np.ndarray, tl1: np.ndarray) -> np.ndarray:
    """The span's low-64 trace id as one u64 sort/search key (EXACT, not
    a hash — lenient trace-id matching is exact low-64 equality)."""
    return (tl1.astype(np.uint64) << np.uint64(32)) | tl0.astype(np.uint64)


def parsed_record(parsed) -> Optional[tuple]:
    """Build one ``append_batch`` argument tuple from a native-parser
    chunk (``ParsedColumns``): compacted payload + per-span columns.
    Numpy-only so MP-tier parse workers (which must not import jax) can
    build records worker-side; service/name/key lanes carry whatever id
    space the parser interned into (the MP dispatcher remaps them
    worker-local -> global before appending). Returns None for an empty
    chunk.

    The payload is the chunk's contiguous byte range unless sampling
    punched >5% holes in it — then it compacts to exactly the kept
    slices, so dropped spans' raw bytes are never persisted as
    unindexed garbage."""
    n = parsed.n
    if n == 0:
        return None
    off = parsed.span_off[:n].astype(np.uint64)
    ln = parsed.span_len[:n].astype(np.uint64)
    lo = int(off[0])
    hi = int((off + ln).max())
    span_bytes = int(ln.sum())
    if span_bytes < (hi - lo) * 95 // 100:
        data = parsed.data
        parts = [
            bytes(data[int(o) : int(o) + int(l)])
            for o, l in zip(off.tolist(), ln.tolist())
        ]
        payload = b"".join(parts)
        new_off = np.concatenate([[0], np.cumsum(ln[:-1])]).astype(np.uint32)
    else:
        payload = bytes(parsed.data[lo:hi])
        new_off = (off - lo).astype(np.uint32)
    return (
        payload,
        new_off,
        parsed.span_len[:n].copy(),
        parsed.tl0[:n].copy(),
        parsed.tl1[:n].copy(),
        parsed.th0[:n].copy(),
        parsed.th1[:n].copy(),
        parsed.svc_id[:n].copy(),
        parsed.rsvc_id[:n].copy(),
        parsed.name_id[:n].copy(),
        parsed.key_id[:n].copy(),
        (parsed.ts_us[:n] // 60_000_000).astype(np.uint32),
        np.where(parsed.has_dur[:n], parsed.dur_us[:n], 0).astype(np.uint64),
        parsed.err[:n].copy(),
    )


def _fsync_dir(directory: str) -> None:
    """Make a rename in ``directory`` durable (same chokepoint idiom as
    snapshot.py / timetier.py — the dir entry itself needs the fsync)."""
    dfd = os.open(directory or ".", os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _presence_bits(vals: np.ndarray) -> np.ndarray:
    """8KB bitmap of which u16 ids occur (ids >= 2^16 are the caller's
    overflow flag — the archive packs svc/rsvc into 16 bits, names can
    exceed it)."""
    bits = np.zeros(1 << 13, np.uint8)  # 65536 bits
    v = np.unique(vals[vals < (1 << 16)]).astype(np.int64)
    np.bitwise_or.at(bits, v >> 3, (1 << (v & 7)).astype(np.uint8))
    return bits


def _has_bit(bits: np.ndarray, i: int) -> bool:
    return bool(bits[i >> 3] & (1 << (i & 7)))


def build_segment_meta(cols: np.ndarray) -> dict:
    """Zone map + presence bitmaps for one sealed segment's index
    columns: lets a search skip whole segments that cannot match
    (VERDICT r4 order 6 — the ES daily-index pruning analog). All
    filters are CONSERVATIVE: absence proves no match, presence proves
    nothing (the row mask still runs)."""
    c = np.asarray(cols)
    if c.shape[0] == 0:
        return dict(
            ts_min=np.uint32(0), ts_max=np.uint32(0),
            svc_bits=np.zeros(1 << 13, np.uint8),
            rsvc_bits=np.zeros(1 << 13, np.uint8),
            name_bits=np.zeros(1 << 13, np.uint8),
            name_overflow=np.uint8(0),
            dur_min=np.uint32(0), dur_max=np.uint32(0),
        )
    svc = c[:, 6] >> 16
    rsvc = c[:, 6] & 0xFFFF
    name = c[:, 7]
    ts = c[:, 9]
    dur = c[:, 10] >> 1
    present = dur[dur > 0]
    return dict(
        ts_min=ts.min(), ts_max=ts.max(),
        svc_bits=_presence_bits(svc),
        rsvc_bits=_presence_bits(rsvc),
        name_bits=_presence_bits(name),
        name_overflow=np.uint8(1 if (name >= (1 << 16)).any() else 0),
        dur_min=present.min() if present.size else np.uint32(0),
        dur_max=present.max() if present.size else np.uint32(0),
    )


def _meta_can_skip(
    meta: Optional[dict],
    *,
    ts_lo_min: int,
    ts_hi_min: int,
    svc_id: Optional[int],
    rsvc_id: Optional[int],
    name_id: Optional[int],
    min_dur: Optional[int],
    max_dur: Optional[int],
) -> bool:
    """True when the zone map PROVES no row of the segment can match."""
    if meta is None:
        return False
    if ts_hi_min < int(meta["ts_min"]) or ts_lo_min > int(meta["ts_max"]):
        return True
    if svc_id is not None and not _has_bit(meta["svc_bits"], svc_id):
        return True
    if rsvc_id is not None and not _has_bit(meta["rsvc_bits"], rsvc_id):
        return True
    if name_id is not None and not int(meta["name_overflow"]):
        if name_id < (1 << 16) and not _has_bit(meta["name_bits"], name_id):
            return True
    clamp = (1 << 31) - 1
    if min_dur is not None and max(min(min_dur, clamp), 1) > int(
        meta["dur_max"]
    ):
        return True
    if max_dur is not None and (
        int(meta["dur_min"]) == 0 or min(max_dur, clamp) < int(meta["dur_min"])
    ):
        return True
    return False


class _Segment:
    """One sealed segment: data file + mmap'd sorted index sidecars +
    a small zone-map/presence sidecar consulted before any row scan."""

    def __init__(self, path: str) -> None:
        self.path = path
        self.ids = np.load(path + ".ids.npy", mmap_mode="r")  # [n] u64 sorted
        self.cols = np.load(path + ".cols.npy", mmap_mode="r")  # [n, COLS] u32
        self.meta: Optional[dict] = None
        try:
            with np.load(path + ".meta.npz") as z:
                self.meta = {k: z[k] for k in z.files}
        except OSError:
            # pre-r5 segment: build the meta once from the cols (one
            # full read) and persist it for the next boot
            try:
                self.meta = build_segment_meta(self.cols)
                tmp = path + ".meta.npz.tmp"
                with open(tmp, "wb") as f:
                    np.savez_compressed(f, **self.meta)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path + ".meta.npz")
                _fsync_dir(os.path.dirname(path))
            except OSError:  # read-only dir etc.: scan without skipping
                pass
        # a retained fd: reads survive retention's unlink (queries that
        # snapshotted views() before the delete still resolve)
        self._fd = os.open(path, os.O_RDONLY)

    def pread(self, off: int, ln: int) -> bytes:
        return os.pread(self._fd, ln, off)

    @property
    def n(self) -> int:
        return int(self.ids.shape[0])

    def bytes_used(self) -> int:
        total = 0
        for p in (
            self.path, self.path + ".ids.npy", self.path + ".cols.npy",
            self.path + ".meta.npz",
        ):
            try:
                total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def close(self) -> None:
        # numpy mmaps close with GC; drop references eagerly
        self.ids = None
        self.cols = None
        if getattr(self, "_fd", None) is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None

    def __del__(self):  # pragma: no cover - GC finalizer
        try:
            self.close()
        except Exception:
            pass


class SpanArchive:
    """Bounded disk archive of raw span JSON with a trace-id index."""

    def __init__(
        self,
        directory: str,
        *,
        max_bytes: int = 2 << 30,
        segment_bytes: int = 64 << 20,
    ) -> None:
        if segment_bytes > (3 << 30):
            # span offsets are segment-absolute u32; a segment may
            # overshoot its bound by one batch (~64MB), so cap well
            # below 4GiB instead of silently wrapping extents
            raise ValueError(
                f"segment_bytes ({segment_bytes}) must be <= 3GiB "
                "(u32 segment-absolute offsets)"
            )
        self.directory = directory
        self.max_bytes = max_bytes
        self.segment_bytes = segment_bytes
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._sealed: List[_Segment] = []  # oldest -> newest
        # path -> _Segment for every sealed segment: a views() snapshot
        # taken while a segment was LIVE holds its path string; if the
        # segment seals (and maybe gets retention-unlinked) while the
        # query still holds that snapshot, the path resolves here to the
        # sealed segment's retained fd instead of FileNotFoundError ->
        # silent [] (ADVICE r4). Retention moves its entry to a small
        # FIFO (`_retired`) so reads survive a bounded churn window
        # without pinning every evicted segment's fd forever.
        self._path_to_seg: Dict[str, _Segment] = {}
        self._retired: List[str] = []  # paths, oldest first, cap 8
        self._live_fh = None
        self._live_path: Optional[str] = None
        self._live_bytes = 0
        self._live_rows: List[np.ndarray] = []  # [n, COLS] u32 chunks
        self._seg_idx = 0
        self._closed = False
        self.spans_written = 0
        self.spans_dropped_retention = 0
        # segments excluded from a search by their zone-map sidecar
        # (host-side observability; exercised by tests)
        self.segments_skipped = 0
        # bit-rot accounting (ISSUE 7): sealed segments the scrubber
        # pulled from service (.quarantine rename) and the spans that
        # went with them — searches skip them instead of failing
        self.segments_quarantined = 0
        self.spans_quarantined = 0
        # disk-exhaustion accounting (ISSUE 13): the archive is a
        # bounded lossy cache, so ENOSPC means drop-and-flag, not crash;
        # at_risk clears on the next successful append (space freed)
        self.enospc_count = 0
        self.spans_dropped_enospc = 0
        self.at_risk = False
        self._recover()

    # -- write side ------------------------------------------------------

    def append_batch(
        self,
        payload: bytes,
        span_off: np.ndarray,
        span_len: np.ndarray,
        tl0: np.ndarray,
        tl1: np.ndarray,
        th0: np.ndarray,
        th1: np.ndarray,
        svc: np.ndarray,
        rsvc: np.ndarray,
        name: np.ndarray,
        key: np.ndarray,
        ts_min: np.ndarray,
        dur: np.ndarray,
        err: np.ndarray,
    ) -> None:
        """Append one parsed batch: the raw payload plus per-span index
        columns. All arrays length n; offsets index into ``payload``."""
        n = int(span_off.shape[0])
        if n == 0:
            return
        rows = np.empty((n, COLS), np.uint32)
        rows[:, 0] = tl0
        rows[:, 1] = tl1
        rows[:, 2] = th0
        rows[:, 3] = th1
        rows[:, 4] = span_off
        rows[:, 5] = span_len
        rows[:, 6] = (svc.astype(np.uint32) << np.uint32(16)) | (
            rsvc.astype(np.uint32) & np.uint32(0xFFFF)
        )
        rows[:, 7] = name.astype(np.uint32)
        rows[:, 8] = key.astype(np.uint32)
        rows[:, 9] = ts_min.astype(np.uint32)
        rows[:, 10] = (
            np.minimum(dur.astype(np.uint64), (1 << 31) - 1).astype(np.uint32)
            << np.uint32(1)
        ) | err.astype(np.uint32)
        frame = _FRAME.pack(_MAGIC, n, len(payload), zlib.crc32(payload))
        with self._lock:
            if self._closed:
                raise RuntimeError("archive is closed")
            try:
                faults.resource_point("archive")
                fh = self._live_file()
                base = self._live_bytes + _FRAME.size + rows.nbytes
                # offsets become absolute within the segment's data file
                rows[:, 4] += np.uint32(base)
                fh.write(frame)
                fh.write(rows.tobytes())
                if faults.is_armed("archive.mid_segment"):
                    fh.flush()  # kernel-visible partial frame for the
                    # in-process crash action (matches post-flush SIGKILL)
                faults.crashpoint("archive.mid_segment")
                fh.write(payload)
                fh.flush()
            except OSError as e:
                if e.errno != errno.ENOSPC:
                    raise
                self._note_enospc_locked(n)
                return
            self.at_risk = False
            # bit-rot injection site (ISSUE 7): the frame's payload is
            # durable — damage it at rest (scrub/recovery must catch it)
            faults.corrupt_point(
                "archive.frame", self._live_path, base, len(payload)
            )
            self._live_bytes = base + len(payload)
            self._live_rows.append(rows)
            self.spans_written += n
            if self._live_bytes >= self.segment_bytes:
                self._seal_live()
                self._enforce_retention()

    # zt-lint: disable=ZT04 — called only from append_batch's critical
    # section; self._lock is already held
    def _note_enospc_locked(self, n: int) -> None:
        """Disk full mid-frame: drop the batch and ABANDON the live
        segment — its file may carry a torn frame tail whose bytes the
        row index never saw, and the seal sidecars need disk we don't
        have. Already-indexed live rows go down with it (counted); boot
        recovery truncates the orphan's torn tail if it survives."""
        self.enospc_count += 1
        self.spans_dropped_enospc += n + sum(
            int(r.shape[0]) for r in self._live_rows
        )
        if not self.at_risk:
            logger.error(
                "archive append hit ENOSPC: raw-span archive degraded "
                "(batches dropped until disk frees)"
            )
        self.at_risk = True
        if self._live_fh is not None:
            try:
                self._live_fh.close()
            except OSError:
                pass
            self._live_fh = None
        self._live_path = None
        self._live_bytes = 0
        self._live_rows = []

    # zt-lint: disable=ZT04 — called only from append_batch's critical
    # section; self._lock is already held
    def _live_file(self):
        if self._live_fh is None:
            self._live_path = os.path.join(
                self.directory, f"arc-{self._seg_idx:08d}.dat"
            )
            self._seg_idx += 1
            self._live_fh = open(self._live_path, "ab")
            self._live_bytes = os.path.getsize(self._live_path)
        return self._live_fh

    # zt-lint: disable=ZT04 — every caller (append_batch, flush, close)
    # holds self._lock around the seal
    def _seal_live(self) -> None:
        """Sort the live rows by low-64 trace id and write the sidecars;
        reopen the segment read-only as mmap."""
        if self._live_fh is None:
            return
        self._live_fh.close()
        self._live_fh = None
        rows = (
            np.concatenate(self._live_rows)
            if self._live_rows
            else np.empty((0, COLS), np.uint32)
        )
        self._live_rows = []
        ids = _id64(rows[:, 0], rows[:, 1])
        order = np.argsort(ids, kind="stable")
        np.save(self._live_path + ".ids.npy", ids[order])
        np.save(self._live_path + ".cols.npy", rows[order])
        with open(self._live_path + ".meta.npz", "wb") as f:
            # compressed: the presence bitmaps are mostly zeros, so the
            # sidecar stays ~KB instead of 25KB (it counts against the
            # retention byte budget like every other sidecar)
            np.savez_compressed(f, **build_segment_meta(rows))
        seg = _Segment(self._live_path)
        self._sealed.append(seg)
        self._path_to_seg[self._live_path] = seg
        self._live_path = None
        self._live_bytes = 0

    def _enforce_retention(self) -> None:
        total = sum(s.bytes_used() for s in self._sealed) + self._live_bytes
        while len(self._sealed) > 1 and total > self.max_bytes:
            old = self._sealed.pop(0)
            total -= old.bytes_used()
            self.spans_dropped_retention += old.n
            # do NOT close: a query holding a views() snapshot may still
            # read through the segment's mmaps/fd — POSIX keeps unlinked
            # files readable until the last reference drops (GC closes)
            for suffix in ("", ".ids.npy", ".cols.npy", ".meta.npz"):
                try:
                    os.remove(old.path + suffix)
                except OSError:
                    pass
            # keep the path resolvable (retained fd) for a bounded churn
            # window; past the cap the oldest retired entry only DROPS
            # its map reference — a views() snapshot taken before the
            # drop may still hold the segment object, so the fd must
            # close by GC when the LAST reference dies, never eagerly
            # (closing here would EBADF a long query mid-read). The cap
            # bounds the map-pinned overhang to ~2 unlinked segments;
            # snapshot-pinned segments free when their query ends.
            self._retired.append(old.path)
            while len(self._retired) > 2:
                self._path_to_seg.pop(self._retired.pop(0), None)

    def flush(self) -> None:
        """Seal the live segment so its spans are index-served (tests,
        shutdown). Cheap no-op when nothing is live."""
        with self._lock:
            if self._live_rows or self._live_fh is not None:
                self._seal_live()
                self._enforce_retention()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            if self._live_rows or self._live_fh is not None:
                self._seal_live()
            self._closed = True
            for s in self._sealed:
                s.close()
            # retired segments hold unlinked fds/mmaps past retention —
            # drop the map so GC releases any not pinned by a live query
            self._path_to_seg.clear()
            self._retired.clear()

    # -- recovery --------------------------------------------------------

    # zt-lint: disable=ZT04 — constructor-time scan; no other thread can
    # hold a reference to the archive yet
    def _recover(self) -> None:
        names = sorted(
            f for f in os.listdir(self.directory)
            if f.startswith("arc-") and f.endswith(".dat")
        )
        for f in names:
            path = os.path.join(self.directory, f)
            self._seg_idx = max(
                self._seg_idx, int(f[len("arc-"):-len(".dat")]) + 1
            )
            if os.path.exists(path + ".ids.npy"):
                try:
                    seg = _Segment(path)
                    self._sealed.append(seg)
                    self._path_to_seg[path] = seg
                    continue
                except Exception:
                    logger.warning("archive: bad sidecars for %s", path)
            # unsealed tail: rebuild rows by scanning frames; truncate a
            # torn final frame (the WAL's torn-tail rule)
            rows, good = self._scan_frames(path)
            if rows:
                self._live_path = path
                self._live_fh = open(path, "ab")
                if good < os.path.getsize(path):
                    self._live_fh.truncate(good)
                self._live_bytes = good
                self._live_rows = rows
                self.spans_written += int(sum(r.shape[0] for r in rows))
            else:
                try:
                    os.remove(path)
                except OSError:
                    pass

    def _scan_frames(self, path: str) -> Tuple[List[np.ndarray], int]:
        rows: List[np.ndarray] = []
        good = 0
        size = os.path.getsize(path)
        with open(path, "rb") as fh:
            while True:
                hdr = fh.read(_FRAME.size)
                if len(hdr) < _FRAME.size:
                    break
                magic, n, plen, crc = _FRAME.unpack(hdr)
                if magic != _MAGIC:
                    break
                need = n * COLS * 4 + plen
                body = fh.read(need)
                if len(body) < need:
                    break
                if zlib.crc32(body[n * COLS * 4:]) != crc:
                    break
                rows.append(
                    np.frombuffer(
                        body, np.uint32, count=n * COLS
                    ).reshape(n, COLS).copy()
                )
                good += _FRAME.size + need
        if good < size:
            logger.warning(
                "archive: truncated torn tail of %s at %d (was %d)",
                path, good, size,
            )
        return rows, good

    # -- read side -------------------------------------------------------

    def views(self):
        """(ids, cols, data_path) per segment, NEWEST first, including a
        sorted view of the live segment. Query paths that touch several
        traces snapshot this ONCE — the live view sorts its rows on
        construction, so per-trace re-snapshots would re-sort per call
        (measured 1881 argsorts for one search before this was shared)."""
        with self._lock:
            out = []
            if self._live_rows and self._live_path:
                rows = np.concatenate(self._live_rows)
                ids = _id64(rows[:, 0], rows[:, 1])
                order = np.argsort(ids, kind="stable")
                out.append((ids[order], rows[order], self._live_path, None))
            for seg in reversed(self._sealed):
                # the SEGMENT object (not its path): its retained fd
                # keeps reads working after retention unlinks the file
                out.append((seg.ids, seg.cols, seg, seg.meta))
            return out

    def _read_spans(self, src, rows: np.ndarray) -> List[bytes]:
        """``src`` is a _Segment (sealed: retained fd) or a path string
        (live segment: never deleted while live)."""
        if isinstance(src, _Segment):
            return [
                src.pread(int(off), int(ln)) for off, ln in rows[:, 4:6]
            ]
        # live-segment path string: the segment may have SEALED (and even
        # been retention-unlinked) since the snapshot was taken — resolve
        # through the sealed segment's retained fd when it has
        with self._lock:
            seg = self._path_to_seg.get(src)
        if seg is not None:
            return [
                seg.pread(int(off), int(ln)) for off, ln in rows[:, 4:6]
            ]
        out = []
        try:
            with open(src, "rb") as fh:
                for off, ln in rows[:, 4:6]:
                    fh.seek(int(off))
                    out.append(fh.read(int(ln)))
        except FileNotFoundError:  # pragma: no cover - bounded-churn miss
            return []
        return out

    def fetch_trace_raw(
        self, tl0: int, tl1: int, th0: int, th1: int, strict: bool,
        views=None,
    ) -> List[bytes]:
        """Raw JSON slices of every archived span whose trace id matches
        (exact low-64; high-64 also compared when ``strict``)."""
        want = np.uint64((tl1 << 32) | tl0)
        slices: List[bytes] = []
        for ids, cols, path, _meta in (
            views if views is not None else self.views()
        ):
            lo = int(np.searchsorted(ids, want, side="left"))
            hi = int(np.searchsorted(ids, want, side="right"))
            if hi <= lo:
                continue
            rows = np.asarray(cols[lo:hi])
            if strict:
                rows = rows[(rows[:, 2] == th0) & (rows[:, 3] == th1)]
            if rows.shape[0]:
                slices.extend(self._read_spans(path, rows))
        return slices

    def candidate_trace_ids(
        self,
        *,
        ts_lo_min: int,
        ts_hi_min: int,
        svc_id: Optional[int] = None,
        rsvc_id: Optional[int] = None,
        name_id: Optional[int] = None,
        min_dur: Optional[int] = None,
        max_dur: Optional[int] = None,
        limit: int = 1000,
        views=None,
    ) -> List[Tuple[int, int]]:
        """Distinct (id64_low, ts) candidates matching the INDEXED
        predicates, newest-first, scanning newest segments first and
        stopping once ``limit`` distinct traces matched (so a narrow
        recent query never reads cold segments). Non-indexed clauses
        (annotationQuery) are the caller's exact post-filter."""
        seen: Dict[int, int] = {}
        for ids, cols, _, meta in (
            views if views is not None else self.views()
        ):
            if _meta_can_skip(
                meta, ts_lo_min=ts_lo_min, ts_hi_min=ts_hi_min,
                svc_id=svc_id, rsvc_id=rsvc_id, name_id=name_id,
                min_dur=min_dur, max_dur=max_dur,
            ):
                # zone map proves no row can match: the segment's cols
                # pages are never touched (ES daily-index pruning analog)
                self.segments_skipped += 1
                continue
            cols = np.asarray(cols)
            mask = (cols[:, 9] >= ts_lo_min) & (cols[:, 9] <= ts_hi_min)
            if svc_id is not None:
                mask &= (cols[:, 6] >> 16) == svc_id
            if rsvc_id is not None:
                mask &= (cols[:, 6] & 0xFFFF) == rsvc_id
            if name_id is not None:
                mask &= cols[:, 7] == name_id
            dur = cols[:, 10] >> 1
            clamp = (1 << 31) - 1  # stored durations clamp here
            if min_dur is not None:
                mask &= dur >= max(min(min_dur, clamp), 1)  # dur 0 = absent
            if max_dur is not None:
                mask &= (dur <= min(max_dur, clamp)) & (dur > 0)
            hit = np.nonzero(mask)[0]
            if hit.size == 0:
                continue
            hit_ids = _id64(cols[hit, 0], cols[hit, 1])
            hit_ts = cols[hit, 9]
            for i64, ts in zip(hit_ids.tolist(), hit_ts.tolist()):
                prev = seen.get(i64)
                if prev is None or ts > prev:
                    seen[i64] = ts
            if len(seen) >= limit:
                break
        # newest first, TRUNCATED to the limit: a single big segment can
        # contribute far more matches than the cap before the loop
        # breaks, and callers pay a trace fetch per returned candidate
        return sorted(seen.items(), key=lambda kv: -kv[1])[:limit]

    def sealed_segment_paths(self) -> List[str]:
        """Data-file paths of every sealed segment — the scrub set (the
        live segment is re-verified by boot recovery, not at rest)."""
        with self._lock:
            return [seg.path for seg in self._sealed]

    def quarantine_segment(self, path: str) -> int:
        """Pull one sealed segment from service: rename its data file +
        sidecars aside (``.quarantine`` — never unlink, it is postmortem
        evidence) and drop it from the read set, so searches SKIP the
        bad frames with accounting instead of failing the query. Returns
        the span count removed. In-flight queries holding a views()
        snapshot keep reading through the segment's retained fd — a
        corrupt payload decodes to a skipped span, never an error."""
        with self._lock:
            for i, seg in enumerate(self._sealed):
                if seg.path == path:
                    self._sealed.pop(i)
                    break
            else:
                return 0
            self._path_to_seg.pop(path, None)
            n = seg.n
            self.segments_quarantined += 1
            self.spans_quarantined += n
            for suffix in ("", ".ids.npy", ".cols.npy", ".meta.npz"):
                try:
                    # zt-lint: disable=ZT12 — quarantine moves already-corrupt bytes ASIDE; the poison file's durability is not a recovery invariant (a lost rename just re-quarantines next boot)
                    os.replace(
                        seg.path + suffix, seg.path + suffix + ".quarantine"
                    )
                except OSError:
                    pass
        logger.warning(
            "archive segment %s quarantined (%d spans out of service)",
            path, n,
        )
        return n

    def counters(self) -> dict:
        with self._lock:
            return {
                "archiveSpansWritten": self.spans_written,
                "archiveSpansDroppedRetention": self.spans_dropped_retention,
                "archiveSearchSegmentsSkipped": self.segments_skipped,
                "archiveSegmentsQuarantined": self.segments_quarantined,
                "archiveSpansQuarantined": self.spans_quarantined,
                "archiveEnospc": self.enospc_count,
                "archiveSpansDroppedEnospc": self.spans_dropped_enospc,
                "archiveAtRisk": int(self.at_risk),
                "archiveSegments": len(self._sealed)
                + (1 if self._live_rows else 0),
                "archiveBytes": sum(s.bytes_used() for s in self._sealed)
                + self._live_bytes,
            }
