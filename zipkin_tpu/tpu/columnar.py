"""Host-side columnar packing: Span objects -> fixed-shape device batches.

The reference's row-oriented object-per-span design
(``zipkin2/Span.java``) is wrong for TPU; the idiomatic core is a struct
of fixed-shape arrays with host-side string interning (SURVEY.md §7
"Design stance"). This module is the boundary: everything above it speaks
:class:`zipkin_tpu.model.span.Span`, everything below speaks arrays.

Ids: trace/span ids are 64/128-bit hex strings in the model; on device
they travel as ``uint32`` lane pairs (TPUs have no useful 64-bit integer
path). ``trace_h`` is a host-computed 32-bit avalanche hash of the full
128-bit id, used for HLL cardinality and as the cheap first lane of
join keys.

Strings: service names / span names are interned into bounded
vocabularies. Id 0 is reserved for "unknown/absent"; overflow beyond
capacity lands in id 0 and is counted (the bounded-cardinality stance the
reference delegates to backends, SURVEY.md §5 long-context row).
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu.internal.hex import lower_64, normalize_trace_id
from zipkin_tpu.model.span import Kind, Span

KIND_TO_ID = {
    None: 0,
    Kind.CLIENT: 1,
    Kind.SERVER: 2,
    Kind.PRODUCER: 3,
    Kind.CONSUMER: 4,
}
ID_TO_KIND = {v: k for k, v in KIND_TO_ID.items()}

_U32 = np.uint32
_MASK32 = 0xFFFFFFFF


def _mix32(x: np.ndarray) -> np.ndarray:
    """numpy mirror of zipkin_tpu.ops.hashing.fmix32 (must stay in sync)."""
    x = x.astype(np.uint32)
    x ^= x >> _U32(16)
    x = (x.astype(np.uint64) * np.uint64(0x85EBCA6B)).astype(np.uint32)
    x ^= x >> _U32(13)
    x = (x.astype(np.uint64) * np.uint64(0xC2B2AE35)).astype(np.uint32)
    x ^= x >> _U32(16)
    return x


def _hash2_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return _mix32(a.astype(np.uint32) ^ _mix32((b.astype(np.uint64) + np.uint64(0x9E3779B9)).astype(np.uint32)))


class Interner:
    """Bounded, thread-safe string -> dense id map. Id 0 is reserved."""

    def __init__(self, capacity: int) -> None:
        self.capacity = capacity
        self._ids: Dict[str, int] = {}
        self._names: List[str] = ["" ]  # id 0
        self._overflow = 0
        self._lock = threading.Lock()

    def intern(self, name: Optional[str]) -> int:
        if not name:
            return 0
        with self._lock:
            got = self._ids.get(name)
            if got is not None:
                return got
            if len(self._names) >= self.capacity:
                self._overflow += 1
                return 0
            nid = len(self._names)
            self._ids[name] = nid
            self._names.append(name)
            return nid

    def lookup(self, nid: int) -> str:
        return self._names[nid] if 0 <= nid < len(self._names) else ""

    def get(self, name: str) -> Optional[int]:
        return self._ids.get(name)

    @property
    def names(self) -> List[str]:
        return self._names[1:]

    @property
    def overflow(self) -> int:
        return self._overflow

    def __len__(self) -> int:
        return len(self._names)


class Vocab:
    """The interners one TPU store shares across batches.

    ``keys`` interns (service, spanName) pairs — the sketch row space for
    latency digests, mirroring the per-(service, span) indexing of the
    reference's index tables (``trace_by_service_span`` in the cassandra
    schema, SURVEY.md §2.3).
    """

    def __init__(self, max_services: int = 1024, max_keys: int = 8192) -> None:
        self.services = Interner(max_services)
        self.span_names = Interner(max_keys)
        self._keys: Dict[Tuple[int, int], int] = {}
        self._key_list: List[Tuple[int, int]] = [(0, 0)]
        self.max_keys = max_keys
        self._overflow = 0
        self._lock = threading.Lock()

    def key_id(self, service_id: int, span_name_id: int) -> int:
        pair = (service_id, span_name_id)
        with self._lock:
            got = self._keys.get(pair)
            if got is not None:
                return got
            if span_name_id != 0 and service_id != 0:
                # pre-reserve the per-service catch-all (svc, 0) BEFORE
                # the named pair — same order as the C interner, so the
                # two id streams stay identical. Past capacity, span-name
                # churn then aggregates under its SERVICE's catch-all row
                # (semantically the "unnamed span mass for this service"
                # row, which id 0 names already share) instead of the
                # global unknown row — the r3 adversarial bench lumped
                # 2.2M spans into one unattributable global row
                # (VERDICT r3 order 5). Service 0 is the global unknown
                # itself: no catch-all (a shadow (0, 0) row would hijack
                # unknown-service mass from row 0).
                ca = (service_id, 0)
                if ca not in self._keys and len(self._key_list) < self.max_keys:
                    cid = len(self._key_list)
                    self._keys[ca] = cid
                    self._key_list.append(ca)
            if len(self._key_list) >= self.max_keys:
                self._overflow += 1
                if span_name_id != 0 and service_id != 0:
                    return self._keys.get((service_id, 0), 0)
                return 0
            kid = len(self._key_list)
            self._keys[pair] = kid
            self._key_list.append(pair)
            return kid

    def append_pair(self, service_id: int, span_name_id: int) -> int:
        """Position-faithful append for REPLAY paths (WAL, snapshots):
        records the pair at the next id with NO derived insertions (no
        catch-all pre-reserve), reproducing a historical id assignment
        verbatim whatever interning rules the writing build used. Live
        ingest must use :meth:`key_id`."""
        pair = (service_id, span_name_id)
        with self._lock:
            got = self._keys.get(pair)
            if got is not None:
                return got
            if len(self._key_list) >= self.max_keys:
                self._overflow += 1
                return 0
            kid = len(self._key_list)
            self._keys[pair] = kid
            self._key_list.append(pair)
            return kid

    def key_pair(self, key_id: int) -> Tuple[int, int]:
        return self._key_list[key_id] if 0 <= key_id < len(self._key_list) else (0, 0)

    def key_ids_for_service(self, service_id: int) -> List[int]:
        return [k for k, (s, _) in enumerate(self._key_list) if s == service_id and k]

    @property
    def num_keys(self) -> int:
        return len(self._key_list)


class SpanColumns(NamedTuple):
    """One fixed-shape batch; every field is a numpy array of length n."""

    trace_h: np.ndarray  # u32 avalanche hash of the full trace id
    tl0: np.ndarray  # u32 trace id low-64 lanes (lo, hi of the low word)
    tl1: np.ndarray
    s0: np.ndarray  # u32 span id lanes
    s1: np.ndarray
    p0: np.ndarray  # u32 parent id lanes (0,0 = absent)
    p1: np.ndarray
    shared: np.ndarray  # bool
    kind: np.ndarray  # i32 KIND_TO_ID
    svc: np.ndarray  # i32 local service id
    rsvc: np.ndarray  # i32 remote service id
    key: np.ndarray  # i32 (service, spanName) sketch row
    err: np.ndarray  # bool
    dur: np.ndarray  # u32 duration µs (clamped), 0 if absent
    has_dur: np.ndarray  # bool
    ts_min: np.ndarray  # u32 epoch minutes (retention ring key)
    valid: np.ndarray  # bool

    @property
    def size(self) -> int:
        return int(self.valid.shape[0])

    @property
    def live(self) -> int:
        return int(self.valid.sum())

    def concat(self, other: "SpanColumns") -> "SpanColumns":
        return SpanColumns(*(np.concatenate([a, b]) for a, b in zip(self, other)))


# Packed wire image: 11 u32 rows = 44 B/span (was 17 rows / 68 B in r2;
# the tunnel transfer is the measured end-to-end bottleneck, so narrow
# lanes ride shared rows — PROFILE_r02.md "next perf dollar").
#   rows 0-8: trace_h, tl0, tl1, s0, s1, p0, p1, dur, ts_min (plain u32)
#   row 9:    svc << 16 | rsvc          (service ids, u16 each)
#   row 10:   key << 8 | kind << 4 | has_dur << 3 | err << 2
#             | shared << 1 | valid     (key u24 + 8 flag bits)
WIRE_ROWS = 11
_PLAIN = ("trace_h", "tl0", "tl1", "s0", "s1", "p0", "p1", "dur", "ts_min")
# hard ceilings implied by the packing (AggConfig defaults: 1024 / 8192)
MAX_WIRE_SERVICES = 1 << 16
MAX_WIRE_KEYS = 1 << 24


def fuse_columns(cols: SpanColumns) -> np.ndarray:
    """One contiguous PACKED u32 image of a batch: ``[..., 11, n]``.

    Host->device transfer cost on a tunneled PJRT backend is dominated by
    per-array dispatch overhead and raw bytes, so the whole batch ships
    as ONE uint32 array — with the narrow fields (service ids, sketch
    key, kind, flag bits) packed into shared rows — and is unpacked on
    device by :func:`zipkin_tpu.parallel.sharded.unfuse_columns` (free
    shifts/masks that XLA fuses into the consuming ops). Accepts
    per-shard stacked fields (leading axes are preserved).
    """
    d = cols._asdict()
    lead = cols.valid.shape[:-1]
    n = cols.valid.shape[-1]
    out = np.empty(lead + (WIRE_ROWS, n), np.uint32)
    for i, name in enumerate(_PLAIN):
        out[..., i, :] = d[name]
    out[..., 9, :] = (
        (d["svc"].astype(np.uint32) << _U32(16)) | d["rsvc"].astype(np.uint32)
    )
    out[..., 10, :] = (
        (d["key"].astype(np.uint32) << _U32(8))
        | (d["kind"].astype(np.uint32) << _U32(4))
        | (d["has_dur"].astype(np.uint32) << _U32(3))
        | (d["err"].astype(np.uint32) << _U32(2))
        | (d["shared"].astype(np.uint32) << _U32(1))
        | d["valid"].astype(np.uint32)
    )
    return out


def empty_columns(n: int) -> SpanColumns:
    z32 = np.zeros(n, _U32)
    return SpanColumns(
        trace_h=z32.copy(), tl0=z32.copy(), tl1=z32.copy(),
        s0=z32.copy(), s1=z32.copy(), p0=z32.copy(), p1=z32.copy(),
        shared=np.zeros(n, bool), kind=np.zeros(n, np.int32),
        svc=np.zeros(n, np.int32), rsvc=np.zeros(n, np.int32),
        key=np.zeros(n, np.int32), err=np.zeros(n, bool),
        dur=z32.copy(), has_dur=np.zeros(n, bool),
        ts_min=z32.copy(), valid=np.zeros(n, bool),
    )


def _pad(n: int, multiple: int) -> int:
    if n == 0:
        return multiple
    return ((n + multiple - 1) // multiple) * multiple


def pack_parsed(
    parsed, vocab: Vocab, pad_to_multiple: int = 1024
) -> SpanColumns:
    """Columns from a native parse (zipkin_tpu.native.parse_spans) —
    the fast ingest path: no Span objects, strings interned straight from
    the wire-buffer slices.

    Interning cost is the host bottleneck at line rate, so slices are
    cached per-call by their raw bytes (names repeat heavily within a
    batch) and the service/name/key lookups share one pass.
    """
    n = parsed.n
    cap = _pad(n, pad_to_multiple)
    data = parsed.data
    mv = memoryview(data)

    svc = np.zeros(cap, np.int32)
    rsvc = np.zeros(cap, np.int32)
    key = np.zeros(cap, np.int32)

    if getattr(parsed, "svc_id", None) is not None:
        # interning already happened inside the native parse
        svc[:n] = parsed.svc_id[:n]
        rsvc[:n] = parsed.rsvc_id[:n]
        key[:n] = parsed.key_id[:n]
        return _assemble(parsed, n, cap, svc, rsvc, key)

    intern_svc = vocab.services.intern
    intern_name = vocab.span_names.intern
    key_id = vocab.key_id
    scache: Dict[bytes, int] = {}
    ncache: Dict[bytes, int] = {}
    kcache: Dict[Tuple[int, int], int] = {}

    soff, slen = parsed.svc_off, parsed.svc_len
    roff, rlen = parsed.rsvc_off, parsed.rsvc_len
    noff, nlen = parsed.name_off, parsed.name_len

    def sid_of(off: int, ln: int) -> int:
        if ln == 0:
            return 0
        raw = bytes(mv[off : off + ln])
        got = scache.get(raw)
        if got is None:
            got = intern_svc(raw.decode("utf-8", "replace").lower())
            scache[raw] = got
        return got

    for i in range(n):
        s = sid_of(soff[i], slen[i])
        svc[i] = s
        rsvc[i] = sid_of(roff[i], rlen[i])
        ln = nlen[i]
        if ln:
            raw = bytes(mv[noff[i] : noff[i] + ln])
            nid = ncache.get(raw)
            if nid is None:
                nid = intern_name(raw.decode("utf-8", "replace").lower())
                ncache[raw] = nid
        else:
            nid = 0
        pair = (s, nid)
        kid = kcache.get(pair)
        if kid is None:
            kid = key_id(s, nid)
            kcache[pair] = kid
        key[i] = kid

    return _assemble(parsed, n, cap, svc, rsvc, key)


def _assemble(parsed, n, cap, svc, rsvc, key) -> SpanColumns:
    def padded(a: np.ndarray, dtype) -> np.ndarray:
        out = np.zeros(cap, dtype)
        out[:n] = a[:n]
        return out

    hi32 = _hash2_np(parsed.th0[:n], parsed.th1[:n])
    trace_h = np.zeros(cap, _U32)
    trace_h[:n] = _hash2_np(_hash2_np(parsed.tl0[:n], parsed.tl1[:n]), hi32)

    valid = np.zeros(cap, bool)
    valid[:n] = True
    return SpanColumns(
        trace_h=trace_h,
        tl0=padded(parsed.tl0, _U32), tl1=padded(parsed.tl1, _U32),
        s0=padded(parsed.s0, _U32), s1=padded(parsed.s1, _U32),
        p0=padded(parsed.p0, _U32), p1=padded(parsed.p1, _U32),
        shared=padded(parsed.shared, bool),
        kind=padded(parsed.kind, np.int32),
        svc=svc, rsvc=rsvc, key=key,
        err=padded(parsed.err, bool),
        dur=padded(parsed.dur_us, _U32),
        has_dur=padded(parsed.has_dur, bool),
        ts_min=padded((parsed.ts_us // 60_000_000).astype(_U32), _U32),
        valid=valid,
    )


def pack_spans(
    spans: Sequence[Span], vocab: Vocab, pad_to_multiple: int = 1024
) -> SpanColumns:
    """Pack spans into a padded columnar batch, interning strings.

    Padding to a small set of bucket sizes keeps jit cache hits high
    (static shapes, SURVEY.md §7 P2 "pad/bucket to static shapes").
    """
    n = len(spans)
    cap = _pad(n, pad_to_multiple)
    cols = empty_columns(cap)

    hi = np.zeros(n, np.uint64)
    lo = np.zeros(n, np.uint64)
    for i, span in enumerate(spans):
        tid = normalize_trace_id(span.trace_id)
        full = int(tid, 16)
        lo[i] = full & 0xFFFFFFFFFFFFFFFF
        hi[i] = full >> 64
        sid = int(span.id, 16)
        cols.s0[i] = sid & _MASK32
        cols.s1[i] = (sid >> 32) & _MASK32
        if span.parent_id:
            pid = int(span.parent_id, 16)
            cols.p0[i] = pid & _MASK32
            cols.p1[i] = (pid >> 32) & _MASK32
        cols.shared[i] = bool(span.shared)
        cols.kind[i] = KIND_TO_ID[span.kind]
        svc = vocab.services.intern(span.local_service_name)
        cols.svc[i] = svc
        cols.rsvc[i] = vocab.services.intern(span.remote_service_name)
        name_id = vocab.span_names.intern(span.name)
        cols.key[i] = vocab.key_id(svc, name_id)
        cols.err[i] = span.is_error
        if span.duration is not None:
            cols.dur[i] = min(int(span.duration), _MASK32)
            cols.has_dur[i] = True
        if span.timestamp is not None:
            cols.ts_min[i] = min(int(span.timestamp) // 60_000_000, _MASK32)
        cols.valid[i] = True

    cols.tl0[:n] = (lo & _MASK32).astype(_U32)
    cols.tl1[:n] = (lo >> np.uint64(32)).astype(_U32)
    hi32 = _hash2_np((hi & _MASK32).astype(_U32), (hi >> np.uint64(32)).astype(_U32))
    cols.trace_h[:n] = _hash2_np(
        _hash2_np(cols.tl0[:n], cols.tl1[:n]), hi32
    )
    return cols


def _route_order(shard_of: np.ndarray, n_shards: int, pad_to_multiple: int):
    """(order, counts, starts, per): lanes stably sorted by shard id, so
    shard ``s`` owns the contiguous slice ``order[starts[s] :
    starts[s] + counts[s]]`` and within-shard insertion order is
    preserved (the linker's first-wins tie-breaks depend on it).

    One radix argsort over a u8 key replaces the per-shard nonzero scans
    (the r2 Python loop cost 8 shards x 17 fields of masked gathers on
    the ingest hot path, VERDICT r2 weak #5); the u8 cast alone makes
    numpy pick its radix path — 15x faster than the i32 stable sort.
    """
    key_dtype = np.uint8 if n_shards < 255 else np.uint16
    order = np.argsort(shard_of.astype(key_dtype), kind="stable")
    counts = np.bincount(shard_of, minlength=n_shards + 1)[:n_shards]
    per = max(int(counts.max()), 1)
    per = ((per + pad_to_multiple - 1) // pad_to_multiple) * pad_to_multiple
    starts = np.zeros(n_shards, np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    return order, counts, starts, per


def _shard_of(cols: SpanColumns, n_shards: int) -> np.ndarray:
    """Trace-affine shard id per lane (invalid lanes -> sink n_shards).

    Trace affinity (all spans of a trace land on one shard) is what makes
    the dependency-link parent joins shard-local — the same invariant the
    reference gets from trace-id–keyed storage partitioning.
    """
    return np.where(
        cols.valid, cols.trace_h % np.uint32(n_shards), n_shards
    ).astype(np.int32)


def route_fused(
    cols: SpanColumns, n_shards: int, pad_to_multiple: int = 256
) -> np.ndarray:
    """Fuse + route in one pass: ``[shards, F, per]`` u32 wire image.

    The whole routed batch is ONE fancy-index gather over the fused
    image (plus an appended zero lane serving as the pad sentinel), so
    multi-chip routing costs the same order as single-chip fusing.
    """
    fz = fuse_columns(cols)  # [F, n]
    if n_shards == 1:
        return fz[None]
    order, counts, starts, per = _route_order(
        _shard_of(cols, n_shards), n_shards, pad_to_multiple
    )
    out = np.zeros((n_shards, fz.shape[0], per), np.uint32)
    for s in range(n_shards):
        c = int(counts[s])
        if c:
            # each destination block is contiguous, so np.take(out=)
            # writes it in one pass — the whole route is one radix sort
            # + n_shards block gathers, ~0.05µs/span at 8 shards
            np.take(fz, order[starts[s] : starts[s] + c], axis=1,
                    out=out[s, :, :c])
    return out


def remap_fused(
    fused: np.ndarray, svc_map: np.ndarray, key_map: np.ndarray
) -> None:  # zt-dispatch-critical: per-span id remap on the dispatch core
    """Remap a packed wire image's service/key id lanes in place through
    ``svc_map``/``key_map`` lookup tables (u32, indexed by old id).

    This is the dispatch-core half of the MP fan-out's worker-local
    interning: workers intern against private vocabs, and the dispatcher
    rewrites row 9 (``svc << 16 | rsvc``) and row 10's key field
    (``key << 8 | flags``) local -> global with three vectorized table
    lookups. Lives here so the packed-row layout is defined in exactly
    one module (see :func:`fuse_columns`). Accepts ``[F, n]`` and
    ``[shards, F, n]`` images alike.
    """
    sr = fused[..., 9, :]
    fused[..., 9, :] = (svc_map[sr >> _U32(16)] << _U32(16)) | svc_map[
        sr & _U32(0xFFFF)
    ]
    kf = fused[..., 10, :]
    fused[..., 10, :] = (key_map[kf >> _U32(8)] << _U32(8)) | (
        kf & _U32(0xFF)
    )


def concat_remap(
    parts, out: np.ndarray
) -> int:  # zt-dispatch-critical: the coalesce gather — one pass per chunk over the whole coalesced image
    """Gather N routed chunk images into one bucket-padded image while
    remapping worker-local ids to global (the span-ring dispatcher's
    coalesce step: the only copy a ready slot ever takes).

    ``parts`` is a sequence of ``(fused, svc_map, key_map)`` where each
    ``fused`` is ``[shards, F, per_i]`` (typically a zero-copy view into
    a ring slot) and the maps are that chunk's local->global LUTs.
    ``out`` is a zeroed ``[shards, F, bucket]`` destination with
    ``bucket >= sum(per_i)``. Chunks land lane-contiguous in order;
    trailing pad lanes stay zero (valid=0 — the same safe-pad invariant
    as :func:`route_fused`). Remapping happens on the copied lanes, so
    the shared-memory source is never written. Returns the number of
    populated lanes per shard.
    """
    off = 0
    for fused, svc_map, key_map in parts:  # zt-lint: disable=ZT09 — bounded by coalesce_max chunks; each iteration is whole-image vectorized
        per = fused.shape[-1]
        dst = out[..., off:off + per]
        dst[:] = fused
        remap_fused(dst, svc_map, key_map)
        off += per
    return off


def route_columns(
    cols: SpanColumns, n_shards: int, pad_to_multiple: int = 256
) -> SpanColumns:
    """Host-side trace-affine routing: split one batch into ``n_shards``
    stacked sub-batches ``[shards, per]`` keyed by trace hash (see
    :func:`_shard_of`). Column-typed variant of :func:`route_fused` for
    callers that want SpanColumns; the ingest path routes the fused
    image directly.
    """
    n = cols.valid.shape[0]
    order, counts, starts, per = _route_order(
        _shard_of(cols, n_shards), n_shards, pad_to_multiple
    )
    j = np.arange(per)
    in_range = j[None, :] < counts[:, None]
    # gather indices with sentinel n -> appended zero/invalid lane
    # (max(n-1, 0): a zero-length batch still routes to all-pad shards)
    take = np.where(
        in_range,
        order[np.minimum(starts[:, None] + j[None, :], max(n - 1, 0))]
        if n else n,
        n,
    ).reshape(-1)

    def route(field: np.ndarray) -> np.ndarray:
        padded = np.concatenate([field, np.zeros(1, field.dtype)])
        return padded[take].reshape(n_shards, per)

    return SpanColumns(*(route(f) for f in cols))
