"""AsyncIngestFeeder: two-stage host pipeline in front of the device.

The reference scales ingest with N Kafka workers per collector
(``KafkaCollectorWorker``, SURVEY.md §2.8 "Kafka partition parallelism"
row); the TPU analog is a host-side pipeline that overlaps the two
serial stages of the fast path:

- **stage A (parse thread)**: ``TpuStorage._fast_parse`` — native JSON
  parse + intern + sample + columnar pack (~0.8 µs/span of host CPU,
  serialized by the vocab intern lock);
- **stage B (dispatch thread)**: ``TpuStorage._fast_dispatch`` —
  sampled archive + device_put + the jit'd step (device-bound).

With one thread per stage and a small bounded queue between them, batch
N+1 parses while the device executes batch N. Ordering across batches
is not guaranteed — irrelevant for the aggregate state (sketch updates
commute) and for the sampled archive (the trace-affine sample is
deterministic per trace id); callers that need strict replay ordering
use the synchronous path.

**Measured result (r2, real chip): the pipeline is SLOWER than the
synchronous loop under CPython** (98-123k vs 155-205k spans/s in the
same windows): the numpy pack and dispatch-side host work hold the GIL,
so the two stages serialize anyway and only the queue/switch overhead
remains. The class is kept as the worker-model seam (the reference's
KafkaCollectorWorker shape) with correctness fully tested — it becomes
profitable under free-threaded Python or a multi-process parse tier,
and callers get backpressure semantics today — but the synchronous
``ingest_json_fast`` loop is the recommended hot path, and bench.py
uses it.
"""

from __future__ import annotations

import queue
import threading
from typing import Optional


class AsyncIngestFeeder:
    """Feeds raw JSON v2 payloads to a TpuStorage through a two-stage
    pipeline (the host half and the device half of ``ingest_json_fast``
    running concurrently). Use as a context manager or call drain().

    submit() blocks when ``depth`` batches are already in flight — the
    backpressure seam (callers shed or buffer per their transport's
    discipline, like the collector's RejectedExecutionError path).
    """

    def __init__(self, store, depth: int = 4, sampler=None) -> None:
        from zipkin_tpu import native

        if not native.available():  # pragma: no cover - no C toolchain
            raise RuntimeError("AsyncIngestFeeder needs the native codec")
        self.store = store
        self.sampler = sampler
        self._parse_q: queue.Queue = queue.Queue(maxsize=depth)
        self._dispatch_q: queue.Queue = queue.Queue(maxsize=depth)
        self._accepted = 0
        self._dropped = 0
        self._fallback = 0
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._parse_t = threading.Thread(target=self._parse_loop, daemon=True)
        self._dispatch_t = threading.Thread(
            target=self._dispatch_loop, daemon=True
        )
        self._parse_t.start()
        self._dispatch_t.start()

    def _parse_loop(self) -> None:
        # After a failure, keep CONSUMING (discarding) so a blocked
        # submit() unblocks and can observe _error — never leave a
        # bounded queue full on the error path (deadlock).
        while True:
            data = self._parse_q.get()
            if data is None:
                self._dispatch_q.put(None)
                return
            if self._error is not None:
                continue
            try:
                work = self.store._fast_parse(data, self.sampler)
                self._dispatch_q.put(("raw", data) if work is None else work)
            except BaseException as e:  # pragma: no cover - defensive
                self._error = e

    def _dispatch_loop(self) -> None:
        from zipkin_tpu.model import codec

        while True:
            item = self._dispatch_q.get()
            if item is None:
                return
            if self._error is not None:
                continue  # drain-and-discard after failure (see above)
            try:
                if isinstance(item, tuple) and item and item[0] == "raw":
                    # payload the fast parser can't take: object path —
                    # apply the SAME boundary sampling the collector
                    # would, or the fallback over-ingests vs the sketches
                    spans = codec.decode_spans(item[1])
                    if self.sampler is not None:
                        kept = [s for s in spans if self.sampler.test(s)]
                    else:
                        kept = spans
                    if kept:
                        self.store.accept(kept).execute()
                    with self._lock:
                        self._fallback += 1
                        self._accepted += len(kept)
                        self._dropped += len(spans) - len(kept)
                    continue
                accepted, dropped, chunks = item
                for parsed, cols in chunks:
                    self.store._fast_dispatch(parsed, cols)
                with self._lock:
                    self._accepted += accepted
                    self._dropped += dropped
            except BaseException as e:  # pragma: no cover - defensive
                self._error = e

    def submit(self, data: bytes) -> None:
        """Enqueue one JSON v2 payload (blocks while the pipeline is
        full; raises if either stage has failed)."""
        while True:
            if self._error is not None:
                raise RuntimeError("feeder failed") from self._error
            try:
                self._parse_q.put(data, timeout=0.1)
                return
            except queue.Full:
                continue

    def drain(self) -> int:
        """Close the pipeline, wait for everything to land, and return the
        accepted span count. The feeder is not reusable afterwards."""
        self._parse_q.put(None)
        self._parse_t.join()
        self._dispatch_t.join()
        if self._error is not None:
            raise RuntimeError("feeder failed") from self._error
        # zt-lint: disable=ZT06 — drain's contract IS the blocking sync:
        # "wait for everything to land" includes the device queue
        self.store.agg.block_until_ready()
        return self._accepted

    def __enter__(self) -> "AsyncIngestFeeder":
        return self

    def __exit__(self, *exc) -> None:
        self.drain()
