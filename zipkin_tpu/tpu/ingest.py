"""The jit'd ingest step and read kernels over :class:`AggState`.

This is the device half of the reference's hot path (SURVEY.md §3.2):
where ``Collector.acceptSpans`` fans bytes out to storage writers, the TPU
tier applies one pure function ``state, batch -> state`` per shard —
sketch scatter updates + a circular-buffer append — compiled once by XLA
and re-used for every batch (static shapes via the packer's bucketed
padding). Reads are pure functions over the same state.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops import hashing, histogram, hll, linker, tdigest
from zipkin_tpu.tpu.columnar import SpanColumns
from zipkin_tpu.tpu.state import (
    CTR_BATCHES,
    CTR_ERRORS,
    CTR_SPANS,
    CTR_WITH_DURATION,
    AggConfig,
    AggState,
)


def ingest_step(config: AggConfig, state: AggState, batch: SpanColumns) -> AggState:
    """Fold one columnar batch into the aggregate state (pure, jit-safe).

    Donate ``state`` at the jit boundary: updates are in-place in HBM.
    """
    valid = batch.valid
    n = valid.shape[0]

    # --- HLL: distinct traces per service + globally --------------------
    h = hashing.fmix32(batch.trace_h)
    svc_rows = jnp.clip(batch.svc, 0, config.max_services - 1)
    new_hll = hll.update(state.hll, svc_rows, h, valid & (batch.svc > 0))
    new_hll = hll.update(
        new_hll, jnp.full((n,), config.global_hll_row, jnp.int32), h, valid
    )

    # --- latency sketches per (service, spanName) key -------------------
    has_dur = valid & batch.has_dur
    new_hist = histogram.update(state.hist, batch.key, batch.dur, has_dur)
    # t-digest: append to the pending buffer; compaction is a SEPARATE
    # program the host dispatches when the buffer would overflow (it
    # tracks pend_pos exactly — every shard advances by the same padded
    # lane count). Round 1 embedded the decision as a lax.cond here; the
    # cond forced full copies of both pending buffers through the
    # conditional every step (~45% of step device time in the r2 profile
    # capture, PROFILE_r02.md) even when no flush ran.
    pend_key, pend_val, pend_pos = _digest_append(
        config, state, batch.key, batch.dur.astype(jnp.float32), has_dur
    )

    # --- ring append (valid lanes first, advance by live count) ---------
    order = jnp.argsort(~valid)  # stable: valid lanes keep order, pad sinks
    live = jnp.sum(valid.astype(jnp.int32))
    lane = jnp.arange(n, dtype=jnp.int32)
    # pad lanes scatter out of range and are DROPPED — they must not
    # clobber retained ring slots ahead of the cursor.
    pos = jnp.where(
        lane < live,
        (state.ring_pos + lane) % config.ring_capacity,
        config.ring_capacity,
    )

    def put(col, new):
        return col.at[pos].set(new[order], mode="drop")

    new_state = state._replace(
        hll=new_hll,
        hist=new_hist,
        pend_key=pend_key,
        pend_val=pend_val,
        pend_pos=pend_pos,
        r_trace_h=put(state.r_trace_h, batch.trace_h),
        r_tl0=put(state.r_tl0, batch.tl0),
        r_tl1=put(state.r_tl1, batch.tl1),
        r_s0=put(state.r_s0, batch.s0),
        r_s1=put(state.r_s1, batch.s1),
        r_p0=put(state.r_p0, batch.p0),
        r_p1=put(state.r_p1, batch.p1),
        r_shared=put(state.r_shared, batch.shared),
        r_kind=put(state.r_kind, batch.kind),
        r_svc=put(state.r_svc, batch.svc),
        r_rsvc=put(state.r_rsvc, batch.rsvc),
        r_err=put(state.r_err, batch.err),
        r_ts_min=put(state.r_ts_min, batch.ts_min),
        r_valid=put(state.r_valid, valid),
        ring_pos=(state.ring_pos + live) % config.ring_capacity,
        counters=state.counters.at[CTR_SPANS].add(live.astype(jnp.uint32))
        .at[CTR_WITH_DURATION].add(jnp.sum(has_dur).astype(jnp.uint32))
        .at[CTR_ERRORS].add(jnp.sum(valid & batch.err).astype(jnp.uint32))
        .at[CTR_BATCHES].add(1),
    )
    return new_state


def _flush_pending_digest(
    config: AggConfig, digest: jnp.ndarray, pend_key: jnp.ndarray, pend_val: jnp.ndarray
):
    """Compact the whole pending buffer into the digests (empty lanes have
    key -1 -> weight 0).

    Split formulation: sort ONLY the pending points into per-key partial
    digests, then fold them in with a row-parallel merge. The round-1
    joint formulation re-sorted all K*C existing centroid lanes every
    flush and dominated the ingest step (66% of device time in the
    profiler capture — see PROFILE_r02.md)."""
    w = (pend_key >= 0).astype(jnp.float32)
    keys = jnp.clip(pend_key, 0, config.max_keys - 1)
    partial = tdigest.compact_points(
        keys, pend_val, w, config.max_keys, config.digest_centroids
    )
    return tdigest.row_merge(digest, partial)


def _digest_append(config: AggConfig, state: AggState, key, val, has_dur):
    """Append the batch's (key, value) points to the pending ring.

    PRECONDITION (host-enforced, see ShardedAggregator.ingest): pend_pos +
    n <= digest_buffer — dynamic_update_slice CLAMPS out-of-range starts,
    which would silently overwrite the buffer tail."""
    batch_key = jnp.where(has_dur, jnp.clip(key, 0, config.max_keys - 1), -1)
    pos = state.pend_pos
    pk = jax.lax.dynamic_update_slice(state.pend_key, batch_key, (pos,))
    pv = jax.lax.dynamic_update_slice(state.pend_val, val, (pos,))
    return pk, pv, pos + key.shape[0]


def flush_digest(config: AggConfig, state: AggState) -> AggState:
    """Reader-side flush: fold any pending values so digest reads are
    complete. Pure; call via jit before quantile queries."""
    d = _flush_pending_digest(config, state.digest, state.pend_key, state.pend_val)
    return state._replace(
        digest=d,
        pend_key=jnp.full_like(state.pend_key, -1),
        pend_val=jnp.zeros_like(state.pend_val),
        pend_pos=jnp.zeros_like(state.pend_pos),
    )


def ring_link_input(state: AggState, ts_lo: jnp.ndarray, ts_hi: jnp.ndarray) -> linker.LinkInput:
    """View the retention ring as a link window restricted to [ts_lo, ts_hi]
    epoch minutes (inclusive)."""
    in_window = (state.r_ts_min >= ts_lo) & (state.r_ts_min <= ts_hi)
    return linker.LinkInput(
        trace_h=state.r_trace_h, tl0=state.r_tl0, tl1=state.r_tl1,
        s0=state.r_s0, s1=state.r_s1, p0=state.r_p0, p1=state.r_p1,
        shared=state.r_shared, kind=state.r_kind,
        svc=state.r_svc, rsvc=state.r_rsvc, err=state.r_err,
        valid=state.r_valid & in_window,
    )


def dependency_links(
    config: AggConfig, state: AggState, ts_lo: jnp.ndarray, ts_hi: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(calls, errors) [S, S] u32 over the ring window — the on-device
    replacement for the zipkin-dependencies batch job (SURVEY.md §3.5)."""
    return linker.link_window(
        ring_link_input(state, ts_lo, ts_hi), config.max_services
    )


def key_quantiles(state: AggState, qs: jnp.ndarray) -> jnp.ndarray:
    """[keys, Q] latency quantiles from the histograms."""
    return histogram.quantile(state.hist, qs)


def key_quantiles_digest(state: AggState, qs: jnp.ndarray) -> jnp.ndarray:
    """[keys, Q] latency quantiles from the t-digests (tighter tails)."""
    return tdigest.quantile(state.digest, qs)


def cardinalities(state: AggState) -> jnp.ndarray:
    """[services+1] estimated distinct traces (last row = global)."""
    return hll.estimate(state.hll)


def jit_ingest(config: AggConfig):
    """The compiled single-shard ingest step with state donation."""
    return jax.jit(
        functools.partial(ingest_step, config), donate_argnums=(0,)
    )
