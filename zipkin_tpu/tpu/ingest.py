"""The jit'd ingest step and read kernels over :class:`AggState`.

This is the device half of the reference's hot path (SURVEY.md §3.2):
where ``Collector.acceptSpans`` fans bytes out to storage writers, the TPU
tier applies one pure function ``state, batch -> state`` per shard —
sketch scatter updates + a circular-buffer append — compiled once by XLA
and re-used for every batch (static shapes via the packer's bucketed
padding). Reads are pure functions over the same state.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

from zipkin_tpu.ops import delta_linker, hashing, histogram, hll, linker, tdigest
from zipkin_tpu.tpu.columnar import SpanColumns
from zipkin_tpu.tpu.state import (
    CTR_BATCHES,
    CTR_ERRORS,
    CTR_SAMPLED_DROPPED,
    CTR_SAMPLED_KEPT,
    CTR_SPANS,
    CTR_WITH_DURATION,
    AggConfig,
    AggState,
)


def lane_bucket(lanes: int, pad_to_multiple: int, cap: int) -> int:  # zt-dispatch-critical: shape-bucket pick on the coalesced dispatch path
    """Static-shape bucket for a coalesced multi-chunk lane count.

    The coalesced dispatch path (span ring, mp_ingest) concatenates N
    routed chunk images into one device batch; feeding the raw sum of
    lane counts to the jitted step would compile a fresh program per
    distinct sum (the ZT03 failure mode). Instead the sum is rounded up
    a doubling ladder anchored at the packer's pad multiple —
    ``pad * 2^k`` capped at the aggregator's lane ceiling — so at most
    ``log2(cap/pad)+1`` programs ever exist. Pad lanes are zero
    (valid=0), the same safe-pad invariant the router relies on.
    """
    b = max(1, int(pad_to_multiple))
    while b < lanes:  # zt-lint: disable=ZT09 — doubling ladder: ≤ log2(cap/pad)+1 trips, independent of span count
        b *= 2
    return min(b, cap) if cap >= lanes else b


def _hll_update(registers, rows, hashes, valid):
    """HLL update with the opt-in Pallas backend (TPU_PALLAS_HLL=1).

    Measured ~11% faster than the XLA scatter on a v5e chip but <1% of
    the ingest step — see ops/pallas_hll.py for the evidence and why the
    XLA path stays the default."""
    if (
        os.environ.get("TPU_PALLAS_HLL", "") in ("1", "true")
        and jax.default_backend() == "tpu"
    ):
        from zipkin_tpu.ops import pallas_hll

        return pallas_hll.update(registers, rows, hashes, valid)
    return hll.update(registers, rows, hashes, valid)


def ingest_step(config: AggConfig, state: AggState, batch: SpanColumns) -> AggState:
    """Fold one columnar batch into the aggregate state (pure, jit-safe).

    Donate ``state`` at the jit boundary: updates are in-place in HBM.
    """
    valid = batch.valid
    n = valid.shape[0]

    # --- HLL: distinct traces per service + globally --------------------
    h = hashing.fmix32(batch.trace_h)
    svc_rows = jnp.clip(batch.svc, 0, config.max_services - 1)
    new_hll = _hll_update(state.hll, svc_rows, h, valid & (batch.svc > 0))
    new_hll = _hll_update(
        new_hll, jnp.full((n,), config.global_hll_row, jnp.int32), h, valid
    )

    # --- latency sketches per (service, spanName) key -------------------
    has_dur = valid & batch.has_dur
    new_hist = histogram.update(state.hist, batch.key, batch.dur, has_dur)
    new_hist_t, new_hist_t_epoch = _hist_slice_update(config, state, batch, has_dur)
    # t-digest: append to the pending buffer; compaction is a SEPARATE
    # program the host dispatches when the buffer would overflow (it
    # tracks pend_pos exactly — every shard advances by the same padded
    # lane count). Round 1 embedded the decision as a lax.cond here; the
    # cond forced full copies of both pending buffers through the
    # conditional every step (~45% of step device time in the r2 profile
    # capture, PROFILE_r02.md) even when no flush ran.
    pend_key, pend_val, pend_pos, pend_ep = _digest_append(
        config, state, batch.key, batch.dur.astype(jnp.float32), has_dur,
        batch.ts_min,
    )

    # --- time-disaggregated current-bucket leaves (tpu/timetier.py) -----
    # Same epoch-ring recycle as the histogram slices, over bucket epochs
    # of time_bucket_minutes: the HLL registers update here per step; the
    # bucketed digest points ride the SAME pending buffer (pend_ep tags
    # each point's bucket) and fold at flush; the edge counts fold at
    # rollup cadence. config.time_buckets is trace-static, so the
    # disabled tier compiles the exact pre-tier step.
    tt = {}
    if config.timetier_enabled:
        w_tt = config.time_buckets
        g = jnp.uint32(config.time_bucket_minutes)
        ep_tt = (batch.ts_min // g).astype(jnp.int32)
        sl_tt = ep_tt % w_tt
        tb_epoch, tb_wipe, tb_keep = _recycle_slots(
            w_tt, state.tb_epoch, sl_tt, ep_tt, valid
        )
        tb_hll = jnp.where(tb_wipe[:, None, None], jnp.uint8(0), state.tb_hll)
        rows_flat = sl_tt * config.hll_rows + svc_rows
        flat = tb_hll.reshape(w_tt * config.hll_rows, -1)
        flat = _hll_update(flat, rows_flat, h, tb_keep & (batch.svc > 0))
        flat = _hll_update(
            flat, sl_tt * config.hll_rows + config.global_hll_row, h, tb_keep
        )
        tt = dict(
            tb_epoch=tb_epoch,
            tb_hll=flat.reshape(tb_hll.shape),
            tb_digest=jnp.where(
                tb_wipe[:, None, None, None], 0.0, state.tb_digest
            ),
            tb_calls=jnp.where(
                tb_wipe[:, None, None], jnp.uint32(0), state.tb_calls
            ),
            tb_errs=jnp.where(
                tb_wipe[:, None, None], jnp.uint32(0), state.tb_errs
            ),
            pend_ep=pend_ep,
        )

    # --- ring append (valid lanes first, advance by live count) ---------
    order = jnp.argsort(~valid)  # stable: valid lanes keep order, pad sinks
    live = jnp.sum(valid.astype(jnp.int32))
    lane = jnp.arange(n, dtype=jnp.int32)
    # pad lanes scatter out of range and are DROPPED — they must not
    # clobber retained ring slots ahead of the cursor.
    pos = jnp.where(
        lane < live,
        (state.ring_pos + lane) % config.ring_capacity,
        config.ring_capacity,
    )

    def put(col, new):
        return col.at[pos].set(new[order], mode="drop")

    # --- tail-sampling verdicts (static off by default) -----------------
    # config.sampling is trace-static, so the off path compiles the exact
    # pre-sampling step: r_keep untouched, counters 5/6 never written.
    counters = (
        state.counters.at[CTR_SPANS].add(live.astype(jnp.uint32))
        .at[CTR_WITH_DURATION].add(jnp.sum(has_dur).astype(jnp.uint32))
        .at[CTR_ERRORS].add(jnp.sum(valid & batch.err).astype(jnp.uint32))
        .at[CTR_BATCHES].add(1)
    )
    r_keep = state.r_keep
    if config.sampling:
        from zipkin_tpu.sampling.device import device_verdict

        keep = device_verdict(
            batch.trace_h, batch.svc, batch.rsvc, batch.key,
            batch.dur, batch.has_dur, batch.err, valid,
            state.s_rate, state.s_tail, state.s_link,
            config.sample_rare_min,
        )
        n_keep = jnp.sum(keep).astype(jnp.uint32)
        counters = (
            counters.at[CTR_SAMPLED_KEPT].add(n_keep)
            .at[CTR_SAMPLED_DROPPED].add(live.astype(jnp.uint32) - n_keep)
        )
        r_keep = put(state.r_keep, keep)

    new_state = state._replace(
        hll=new_hll,
        hist=new_hist,
        hist_t=new_hist_t,
        hist_t_epoch=new_hist_t_epoch,
        pend_key=pend_key,
        pend_val=pend_val,
        pend_pos=pend_pos,
        r_trace_h=put(state.r_trace_h, batch.trace_h),
        r_tl0=put(state.r_tl0, batch.tl0),
        r_tl1=put(state.r_tl1, batch.tl1),
        r_s0=put(state.r_s0, batch.s0),
        r_s1=put(state.r_s1, batch.s1),
        r_p0=put(state.r_p0, batch.p0),
        r_p1=put(state.r_p1, batch.p1),
        r_shared=put(state.r_shared, batch.shared),
        r_kind=put(state.r_kind, batch.kind),
        r_svc=put(state.r_svc, batch.svc),
        r_rsvc=put(state.r_rsvc, batch.rsvc),
        r_err=put(state.r_err, batch.err),
        r_ts_min=put(state.r_ts_min, batch.ts_min),
        r_valid=put(state.r_valid, valid),
        r_keep=r_keep,
        r_rolled=put(state.r_rolled, jnp.zeros((n,), bool)),
        ring_pos=(state.ring_pos + live) % config.ring_capacity,
        # incremental-ctx watermark: the rollup cadence guarantees this
        # never exceeds rollup_segment before the next ctx advance
        ctx_delta=state.ctx_delta + live,
        counters=counters,
        **tt,
    )
    return new_state


def _recycle_slots(num_slots, stored_epoch, slot, ep, active):
    """Epoch-ring slot management shared by the histogram slices and link
    rollups: a slot is zeroed ("wiped") when a batch brings it a NEWER
    absolute epoch; items older than what the slot then holds are dropped
    from the windowed view — the late-arrival semantics of the
    reference's daily indices, where a late span lands in an old daily
    index that queries no longer scan (SURVEY.md §2.3).

    Returns (new_epoch [D], wipe [D] bool, keep [n] bool).
    """
    slot_ep = jnp.full((num_slots,), -1, jnp.int32).at[slot].max(
        jnp.where(active, ep, -1)
    )
    new_epoch = jnp.maximum(stored_epoch, slot_ep)
    wipe = slot_ep > stored_epoch
    keep = active & (ep == new_epoch[slot])
    return new_epoch, wipe, keep


def _slots_in_window(epoch, lo_unit, hi_unit):
    """[D] bool: which epoch-ring slots hold a bucket intersecting the
    window (whole-bucket granularity, as when the reference merges the
    daily rollup rows of a lookback — SURVEY.md §3.5)."""
    return (epoch >= 0) & (epoch >= lo_unit) & (epoch <= hi_unit)


def _masked_slot_sum(sel, arr):
    """Sum [D, ...] over the slots selected by ``sel`` (dtype-preserving)."""
    return jnp.sum(jnp.where(sel[:, None, None], arr, 0), axis=0).astype(arr.dtype)


def _hist_slice_update(config: AggConfig, state: AggState, batch, has_dur):
    """Fold durations into the time-sliced histograms (slice = epoch % T,
    recycled per :func:`_recycle_slots`; the all-time ``hist`` keeps every
    count regardless)."""
    t = config.hist_slices
    ep = (batch.ts_min // jnp.uint32(config.hist_slice_minutes)).astype(jnp.int32)
    sl = ep % t
    new_epoch, wipe, ok = _recycle_slots(t, state.hist_t_epoch, sl, ep, has_dur)
    hist_t = jnp.where(wipe[:, None, None], jnp.uint32(0), state.hist_t)
    b = histogram.bucket_of(batch.dur)
    k = jnp.clip(batch.key.astype(jnp.int32), 0, config.max_keys - 1)
    hist_t = hist_t.at[sl, k, b].add(ok.astype(jnp.uint32))
    return hist_t, new_epoch


def _flush_pending_digest(
    config: AggConfig, digest: jnp.ndarray, pend_key: jnp.ndarray, pend_val: jnp.ndarray
):
    """Compact the whole pending buffer into the digests (empty lanes have
    key -1 -> weight 0).

    Split formulation: sort ONLY the pending points into per-key partial
    digests, then fold them in with a row-parallel merge. The round-1
    joint formulation re-sorted all K*C existing centroid lanes every
    flush and dominated the ingest step (66% of device time in the
    profiler capture — see PROFILE_r02.md)."""
    w = (pend_key >= 0).astype(jnp.float32)
    keys = jnp.clip(pend_key, 0, config.max_keys - 1)
    partial = tdigest.compact_points(
        keys, pend_val, w, config.max_keys, config.digest_centroids
    )
    return tdigest.row_merge(digest, partial)


def _digest_append(config: AggConfig, state: AggState, key, val, has_dur,
                   ts_min=None):
    """Append the batch's (key, value) points to the pending ring.

    PRECONDITION (host-enforced, see ShardedAggregator.ingest): pend_pos +
    n <= digest_buffer — dynamic_update_slice CLAMPS out-of-range starts,
    which would silently overwrite the buffer tail."""
    batch_key = jnp.where(has_dur, jnp.clip(key, 0, config.max_keys - 1), -1)
    pos = state.pend_pos
    pk = jax.lax.dynamic_update_slice(state.pend_key, batch_key, (pos,))
    pv = jax.lax.dynamic_update_slice(state.pend_val, val, (pos,))
    pe = state.pend_ep
    if config.timetier_enabled and ts_min is not None:
        # bucket-epoch tag per point; validity is re-checked against
        # tb_epoch at FLUSH time, so a slot recycled between append and
        # flush drops its stale points (late-arrival semantics)
        ep = (ts_min // jnp.uint32(config.time_bucket_minutes)).astype(
            jnp.int32
        )
        pe = jax.lax.dynamic_update_slice(
            pe, jnp.where(has_dur, ep, -1), (pos,)
        )
    return pk, pv, pos + key.shape[0], pe


def _flush_pending_tt(config: AggConfig, tb_epoch, tb_digest, pend_key,
                      pend_val, pend_ep):
    """Fold the pending points into their bucket slots' compact digests:
    one compact_points segmented by (bucket slot, key) over W*K rows,
    then a row-parallel merge — the same split formulation as the
    cumulative flush. Points whose bucket epoch no longer matches the
    slot (recycled since append, or older than the ring) fold nowhere.
    Per-slot segmentation keeps bucket contents independent of the other
    epochs sharing the buffer — the property the windowed bit-identity
    oracle (tests/test_timetier.py) rests on."""
    w_tt = config.time_buckets
    k = config.max_keys
    cw = config.time_digest_centroids
    sl = jnp.where(pend_ep >= 0, pend_ep % w_tt, 0)
    live = (pend_ep >= 0) & (pend_key >= 0) & (tb_epoch[sl] == pend_ep)
    w = live.astype(jnp.float32)
    keys = jnp.clip(pend_key, 0, k - 1)
    partial = tdigest.compact_points(
        sl * k + keys, pend_val, w, w_tt * k, cw
    )
    merged = tdigest.row_merge(tb_digest.reshape(w_tt * k, cw, 2), partial)
    return merged.reshape(w_tt, k, cw, 2)


def flush_digest(config: AggConfig, state: AggState) -> AggState:
    """Reader-side flush: fold any pending values so digest reads are
    complete. Pure; call via jit before quantile queries."""
    d = _flush_pending_digest(config, state.digest, state.pend_key, state.pend_val)
    tt = {}
    if config.timetier_enabled:
        tt = dict(
            tb_digest=_flush_pending_tt(
                config, state.tb_epoch, state.tb_digest,
                state.pend_key, state.pend_val, state.pend_ep,
            ),
            pend_ep=jnp.full_like(state.pend_ep, -1),
        )
    return state._replace(
        digest=d,
        pend_key=jnp.full_like(state.pend_key, -1),
        pend_val=jnp.zeros_like(state.pend_val),
        pend_pos=jnp.zeros_like(state.pend_pos),
        **tt,
    )


def ring_link_input(state: AggState) -> linker.LinkInput:
    """View the retention ring as a link window (all valid lanes; use the
    ``emit`` mask of link_window/link_edges for time filtering so parent
    joins keep full-ring context)."""
    r = state.r_valid.shape[0]
    lane = jnp.arange(r, dtype=jnp.int32)
    return linker.LinkInput(
        trace_h=state.r_trace_h, tl0=state.r_tl0, tl1=state.r_tl1,
        s0=state.r_s0, s1=state.r_s1, p0=state.r_p0, p1=state.r_p1,
        shared=state.r_shared, kind=state.r_kind,
        svc=state.r_svc, rsvc=state.r_rsvc, err=state.r_err,
        valid=state.r_valid,
        # age since the cursor: the cursor's own lane is the OLDEST live
        # span (next to be overwritten), so tie-breaks stay first-wins in
        # true insertion order across ring wraps (ADVICE r2)
        seq=(lane - state.ring_pos) % r,
    )


def ctx_struct(state: AggState) -> delta_linker.CtxStruct:
    """View the persistent incremental-ctx leaves as a CtxStruct."""
    return delta_linker.CtxStruct(
        order=state.ctx_order, keys=state.ctx_keys,
        rid_c=state.ctx_rid_c, rid_f=state.ctx_rid_f, inv=state.ctx_inv,
        safe_sh=state.ctx_safe_sh, safe_ns=state.ctx_safe_ns,
        safe_fsh=state.ctx_safe_fsh,
        pos=state.ctx_pos, delta=state.ctx_delta,
    )


def fresh_link_context(config: AggConfig, state: AggState) -> linker.LinkContext:
    """The fresh-read link context via the incremental delta formulation:
    persistent ctx + since-advance delta segment, bit-identical to
    ``linker.link_context(ring_link_input(state))`` (the from-scratch
    oracle) but without any full-ring sort."""
    return delta_linker.delta_link_context(
        ring_link_input(state), ctx_struct(state), config.rollup_segment
    )


def rollup_step(config: AggConfig, state: AggState) -> AggState:
    """Link the half-ring the cursor will overwrite next and fold the
    edges into per-time-bucket rollup matrices, then mark those lanes
    rolled (they stop emitting edges but stay JOIN-VISIBLE until
    physically overwritten, so live children still resolve them).

    This is the reference's zipkin-dependencies batch job run on-device
    ahead of eviction (SURVEY.md §3.5): links are attributed to the
    bucket of the child span's timestamp (like the daily ``dependency``
    rows), parents resolve against the FULL ring (whole-trace context),
    and a bucket slot is recycled — zeroed — when a newer epoch folds in.
    The host dispatches this before writes since the last rollup exceed
    ``config.rollup_segment`` (see ShardedAggregator.ingest), so no valid
    span is ever overwritten without its links being preserved.

    ISSUE 5: this is also where the persistent incremental link ctx
    ADVANCES — the delta-merge resolve doubles as the rollup's emit
    context (one resolve serves both), and the refreshed ctx is what
    makes the next fresh read pay only its own since-rollup delta.
    """
    x = ring_link_input(state)
    # x.seq is age-since-cursor: the lanes the cursor will overwrite next
    # are exactly the oldest rollup_segment ranks
    to_roll = state.r_valid & ~state.r_rolled & (x.seq < config.rollup_segment)

    bm = jnp.uint32(config.bucket_minutes)
    bucket_abs = (state.r_ts_min // bm).astype(jnp.int32)
    d = config.link_buckets
    slot = bucket_abs % d
    new_epoch, wipe, emit = _recycle_slots(
        d, state.rollup_epoch, slot, bucket_abs, to_roll
    )

    cs, parent, anc, root_ok, ctx = delta_linker.advance(
        x, ctx_struct(state), config.rollup_segment
    )
    calls_d, errs_d = linker.emit_links_bucketed(
        ctx, slot, d, emit, config.max_services
    )
    rollup_calls = jnp.where(wipe[:, None, None], jnp.uint32(0), state.rollup_calls)
    rollup_errs = jnp.where(wipe[:, None, None], jnp.uint32(0), state.rollup_errs)
    # time-tier edge fold: the SAME resolve emits a second bucketed pass
    # at time_bucket_minutes granularity into the current-bucket edge
    # planes. Slot recycle for these lives in the ingest step (shared
    # tb_epoch); a lane whose bucket epoch is no longer current in its
    # slot emits nowhere (late-arrival semantics).
    tt = {}
    if config.timetier_enabled:
        w_tt = config.time_buckets
        g = jnp.uint32(config.time_bucket_minutes)
        ep_tt = (state.r_ts_min // g).astype(jnp.int32)
        sl_tt = ep_tt % w_tt
        emit_tt = to_roll & (state.tb_epoch[sl_tt] == ep_tt)
        calls_tt, errs_tt = linker.emit_links_bucketed(
            ctx, sl_tt, w_tt, emit_tt, config.max_services
        )
        tt = dict(
            tb_calls=state.tb_calls + calls_tt,
            tb_errs=state.tb_errs + errs_tt,
        )
    return state._replace(
        rollup_calls=rollup_calls + calls_d,
        rollup_errs=rollup_errs + errs_d,
        rollup_epoch=new_epoch,
        **tt,
        # rolled lanes stop emitting but stay join-visible (r_valid keeps
        # them in the parent table until the cursor overwrites them) — so
        # a live child written shortly after its parent rolled still
        # resolves full tree context at query or rollup time
        r_rolled=state.r_rolled | to_roll,
        ctx_order=cs.order, ctx_keys=cs.keys,
        ctx_rid_c=cs.rid_c, ctx_rid_f=cs.rid_f, ctx_inv=cs.inv,
        ctx_safe_sh=cs.safe_sh, ctx_safe_ns=cs.safe_ns,
        ctx_safe_fsh=cs.safe_fsh,
        ctx_parent=parent, ctx_anc=anc, ctx_root=root_ok,
        ctx_pos=cs.pos, ctx_delta=cs.delta,
    )


def rolled_links(
    config: AggConfig, state: AggState, ts_lo: jnp.ndarray, ts_hi: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(calls, errors) [S, S] u32 from the PRE-AGGREGATED rollup buckets
    alone — the exact read the reference serves from its daily
    ``dependency`` table (SURVEY.md §3.5 "read PRE-AGGREGATED daily link
    rows ... merge days"). Correct whenever the window cannot intersect
    any span resident in the live ring (the host tracks the resident
    time range); costs a masked slot-sum instead of the ring lexsort."""
    bm = config.bucket_minutes
    lo_b = (ts_lo // jnp.uint32(bm)).astype(jnp.int32)
    hi_b = (ts_hi // jnp.uint32(bm)).astype(jnp.int32)
    sel = _slots_in_window(state.rollup_epoch, lo_b, hi_b)
    return (
        _masked_slot_sum(sel, state.rollup_calls),
        _masked_slot_sum(sel, state.rollup_errs),
    )


def dependency_links(
    config: AggConfig,
    state: AggState,
    ts_lo: jnp.ndarray,
    ts_hi: jnp.ndarray,
    ctx: linker.LinkContext = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(calls, errors) [S, S] u32 over [ts_lo, ts_hi] epoch minutes —
    live-ring links merged with the rolled-up buckets in the window (the
    reference's "merge days: sum callCount/errorCount", SURVEY.md §3.5).

    Pass a precomputed ``ctx`` (see linker.link_context) to skip the
    ring-sort half — the aggregator caches one per state version.
    """
    if ctx is None:
        # zt-lint: disable=ZT07 — dead branch on the fresh path: spmd_edges_fresh always passes the delta ctx (fresh_link_context); this fallback serves warm-read/test callers where the full rebuild is the point
        ctx = linker.link_context(ring_link_input(state))
    in_window = (state.r_ts_min >= ts_lo) & (state.r_ts_min <= ts_hi)
    calls, errors = linker.emit_links(
        ctx, state.r_valid & ~state.r_rolled & in_window, config.max_services
    )
    rc, re = rolled_links(config, state, ts_lo, ts_hi)
    return calls + rc, errors + re


def key_quantiles(state: AggState, qs: jnp.ndarray) -> jnp.ndarray:
    """[keys, Q] latency quantiles from the histograms."""
    return histogram.quantile(state.hist, qs)


def windowed_hist(
    config: AggConfig, state: AggState, ts_lo: jnp.ndarray, ts_hi: jnp.ndarray
) -> jnp.ndarray:
    """[keys, BUCKETS] histogram summed over the time slices intersecting
    [ts_lo, ts_hi] epoch minutes — the windowed-percentile source.
    Coverage is the most recent T*slice_minutes; older windows return
    empty rows (callers fall back to the all-time ``hist``)."""
    sm = config.hist_slice_minutes
    lo_e = (ts_lo // jnp.uint32(sm)).astype(jnp.int32)
    hi_e = (ts_hi // jnp.uint32(sm)).astype(jnp.int32)
    sel = _slots_in_window(state.hist_t_epoch, lo_e, hi_e)
    return _masked_slot_sum(sel, state.hist_t)


def key_quantiles_digest(state: AggState, qs: jnp.ndarray) -> jnp.ndarray:
    """[keys, Q] latency quantiles from the t-digests (tighter tails)."""
    return tdigest.quantile(state.digest, qs)


def cardinalities(state: AggState) -> jnp.ndarray:
    """[services+1] estimated distinct traces (last row = global)."""
    return hll.estimate(state.hll)


def tt_sketches(
    config: AggConfig,
    state: AggState,
    lo_ep: jnp.ndarray,
    hi_ep: jnp.ndarray,
    ctx: linker.LinkContext = None,
):
    """Read the time-tier slots whose bucket epoch falls in
    ``[lo_ep, hi_ep]`` as ONE mergeable per-shard part:

    - ``epoch`` [W] i32: the slot epochs (host computes actual coverage),
    - ``regs``  [S+1, m] u8: register-max over selected slots,
    - ``digest`` [K, Cw, 2] f32: row-parallel recluster of the selected
      slots' compact digests (one row_merge over the W*Cw concat, the
      merge_many idiom),
    - ``calls``/``errs`` [S, S] u32: the same live-ring + rolled split
      as :func:`dependency_links`, at bucket granularity — un-rolled
      ring lanes whose bucket epoch falls in the range emit through
      ``ctx`` (pass the cached one to skip the ring-sort half), rolled
      lanes come from the ``tb_calls``/``tb_errs`` planes. Every lane
      is in exactly one of the two, so the split is exact.

    The sealer calls this with lo==hi (one bucket -> one segment); the
    windowed query path calls it for the unsealed suffix. The tier's
    query side never touches archive scans (lint rule ZT07 fences it)."""
    sel = _slots_in_window(state.tb_epoch, lo_ep, hi_ep)
    regs = jnp.max(
        jnp.where(sel[:, None, None], state.tb_hll, jnp.uint8(0)), axis=0
    )
    d = state.tb_digest  # [W, K, Cw, 2]
    w_tt, k, cw, _ = d.shape
    dm = jnp.stack(
        [d[..., 0], jnp.where(sel[:, None, None], d[..., 1], 0.0)], axis=-1
    )
    all_c = jnp.moveaxis(dm, 0, 1).reshape(k, w_tt * cw, 2)
    digest = tdigest.row_merge(jnp.zeros((k, cw, 2), jnp.float32), all_c)
    if ctx is None:
        ctx = fresh_link_context(config, state)
    g = jnp.uint32(config.time_bucket_minutes)
    ep_lane = (state.r_ts_min // g).astype(jnp.int32)
    in_w = (ep_lane >= lo_ep) & (ep_lane <= hi_ep)
    live_c, live_e = linker.emit_links(
        ctx, state.r_valid & ~state.r_rolled & in_w, config.max_services
    )
    calls = live_c + _masked_slot_sum(sel, state.tb_calls)
    errs = live_e + _masked_slot_sum(sel, state.tb_errs)
    return state.tb_epoch, regs, digest, calls, errs


@functools.lru_cache(maxsize=None)
def jit_ingest(config: AggConfig):
    """The compiled single-shard ingest step with state donation.

    Cached per config (AggConfig is a hashable NamedTuple): callers may
    treat this as cheap — repeat calls return the SAME jitted wrapper,
    so its trace cache persists instead of recompiling per call."""
    return jax.jit(
        functools.partial(ingest_step, config), donate_argnums=(0,)
    )
