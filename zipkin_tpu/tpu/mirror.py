"""Epoch-published read mirror: lock-free concurrent query serving.

QUERY_SLO_r07 proved the read path was lock-bound, not device-bound:
with 8 reader threads, ``lock_wait`` was 77.5% of attributed query time
(waiter high-water 7/8, device only 13.8%) and query_wall p99 was
136.8 ms against the 50 ms north-star. The fix is the "Fast Concurrent
Data Sketches" publication pattern (PAPERS.md, ROADMAP item 4) at
system scale: a single publisher takes the aggregator lock ONCE per
epoch, runs the existing one-transfer packed read programs, unpacks the
results into an immutable :class:`MirrorSnapshot`, and publishes it
behind the same seqlock generation stamp ``obs/recorder.py`` fuzz-tests
— readers spin-retry on a torn (odd) generation and otherwise serve
entirely without locks, stamping each answer with its staleness age.

Publication protocol (the recorder's writer/reader idiom, verbatim):

- writer: ``gen += 1`` (odd = publish in progress) → swap the snapshot
  reference → ``gen += 1`` (even = stable). One writer at a time — the
  windows ticker is the only publisher in production; the boot path
  publishes before the ticker starts.
- reader: up to ``_TORN_RETRIES`` times, read ``gen``; if odd, retry;
  copy the snapshot reference; if ``gen`` is unchanged the copy is
  consistent. Retries beyond the cap mean a publisher died mid-swap
  (impossible without a killed thread) — take the read.

Staleness contract: a snapshot whose ``write_version`` still matches
the aggregator's is FRESH (age 0 — no query-visible mutation happened
since publish, the same version reasoning ``store._cached_read`` uses)
and serves unconditionally. A version-STALE snapshot carries age
now − published_at and serves only when BOTH hold: (1) the caller may
see staleness at all — an explicit per-request ``staleness_ms``, a
brownout cache-first/cache-only read mode, or an actually-contended
aggregator lock (the store probes non-blocking; on a quiet lock an
exact read is cheap, so default requests stay exact — the posture
``_cached_read`` established for its brownout staleness); and (2) the
age is within the effective bound: the per-request ``staleness_ms``
when given, else ``max_stale_ms`` (``TPU_MIRROR_MAX_STALE_MS``,
default 5000 — the number the ``query_mirror_staleness`` SLO is
bounded by). ``staleness_ms <= 0`` is the per-request escape hatch
back to the lock path, and ``TPU_READ_MIRROR=0`` disables the mirror
wholesale.

What the mirror holds is demand-keyed: the store registers each read's
cache key + compute closure on a mirror miss (seeding the dashboard
defaults at construction so the first post-boot serve is already
lock-free), the publisher computes every registered key under its one
lock hold, and keys not served for a while expire so shifting query
windows cannot grow the registry without bound. Values are the RAW
read-program outputs at ``_cached_read`` granularity — the exact
objects the fresh path would have produced — so mirror-vs-fresh parity
at the publish instant is byte-identical by construction.

Lint: ZT10 (``lint/checkers/mirrorread.py``) statically enforces that
functions marked ``# zt-mirror-served`` never acquire the aggregator
lock; the store's serve path carries the marker.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import nullcontext
from typing import Callable, Dict, Optional, Tuple

from zipkin_tpu import obs
from zipkin_tpu.obs import querytrace

logger = logging.getLogger(__name__)

# Same cap as the recorder's fuzz-tested reader: retries beyond this
# mean a publisher died mid-swap (impossible without a killed thread).
_TORN_RETRIES = 1000

DEFAULT_MAX_STALE_MS = 5000.0


def _env_on(name: str, default: str = "1") -> bool:
    return os.environ.get(name, default).strip().lower() \
        not in ("0", "false", "no")


class MirrorSnapshot:
    """One published epoch: immutable after construction.

    ``values`` maps the store's read-cache keys to the raw read-program
    outputs computed under the publisher's single lock hold;
    ``write_version`` is the aggregator version they were computed at
    (captured inside the hold, so every value is consistent with it).
    """

    __slots__ = (
        "values", "write_version", "published_at", "generation",
        "publish_ms",
    )

    def __init__(
        self,
        values: Dict[str, object],
        write_version: int,
        published_at: float,
        generation: int,
        publish_ms: float,
    ) -> None:
        self.values = values
        self.write_version = write_version
        self.published_at = published_at
        self.generation = generation
        self.publish_ms = publish_ms


class ReadMirror:
    """The publisher/reader pair around one store's aggregator.

    ``agg_provider`` resolves the aggregator lazily (``store.clear()``
    swaps it wholesale, same contract as the querytrace lock provider).
    Serve-path counter writes are GIL-atomic and tolerated torn by
    readers — debug-gauge contract, same as ``obs/device.py``.
    """

    # demand keys not served for this many publishes are dropped
    # (seeded keys are pinned); shifting endTs windows register fresh
    # keys every few minutes, so expiry is what bounds the registry
    DEMAND_TTL_PUBLISHES = 8

    def __init__(
        self,
        agg_provider: Callable,
        max_stale_ms: Optional[float] = None,
        enabled: Optional[bool] = None,
        max_keys: int = 64,
    ) -> None:
        self._agg = agg_provider
        self.enabled = (
            _env_on("TPU_READ_MIRROR") if enabled is None else bool(enabled)
        )
        self.max_stale_ms = (
            float(os.environ.get("TPU_MIRROR_MAX_STALE_MS",
                                 DEFAULT_MAX_STALE_MS))
            if max_stale_ms is None else float(max_stale_ms)
        )
        self.max_keys = max_keys
        # seqlock state: gen even = self._snap is stable, odd = a
        # publish is swapping it. Only the publisher writes either.
        self.gen = 0
        self._snap: Optional[MirrorSnapshot] = None
        # demand registry: key -> [compute, last_used_publish, pinned].
        # The lock covers registration and expiry only — the serve path
        # touches the registry with one GIL-atomic dict read + item
        # write (last-used refresh) and never blocks on it.
        self._demand: Dict[str, list] = {}
        self._demand_lock = threading.Lock()
        self._dirty = False
        # ledger (torn reads tolerated; see class docstring)
        self.publishes = 0
        self.publish_skips = 0
        self.publish_backoffs = 0
        self._publish_done_at: Optional[float] = None
        self.last_publish_ms = 0.0
        self.publish_ms_sum = 0.0
        self.serves = 0
        self.stale_serves = 0
        self.misses = 0
        self.serve_age_ms = 0.0
        self.serve_age_max_ms = 0.0
        self.demand_overflow = 0
        # scale-out seam (serving/, ISSUE 19): called with each newly
        # published snapshot AFTER the swap — outside the aggregator
        # lock, so shm serialization can never stretch the one hold.
        # The store installs it via attach_mirror_segment().
        self.segment_sink: Optional[Callable] = None
        self.segment_sink_errors = 0

    # -- demand registry (serving threads) -------------------------------

    def register(self, key: str, compute: Callable,
                 pinned: bool = False) -> bool:
        """Ask the publisher to carry ``key`` from the next epoch on.
        Called on a mirror miss (the read falls through to the lock path
        this once); bounded — a full registry refuses new unpinned keys
        so a key-churning client cannot grow publish cost unboundedly."""
        if not self.enabled:
            return False
        with self._demand_lock:
            ent = self._demand.get(key)
            if ent is not None:
                ent[1] = self.publishes
                return True
            if len(self._demand) >= self.max_keys and not pinned:
                self.demand_overflow += 1
                return False
            self._demand[key] = [compute, self.publishes, bool(pinned)]
            self._dirty = True
            return True

    # -- reader side (lock-free) -----------------------------------------

    def snapshot(self) -> Optional[MirrorSnapshot]:  # zt-mirror-served: seqlock spin + one reference copy; no lock of any kind
        """The current stable snapshot via the seqlock read protocol."""
        for _ in range(_TORN_RETRIES):
            g1 = self.gen
            if g1 & 1:
                continue  # publish in progress: spin
            snap = self._snap
            if self.gen == g1:
                return snap
        return self._snap  # publisher died mid-swap: take the read

    def serve(self, key: str, bound_ms: Optional[float],
              live_version: int,
              allow_stale: bool = True) -> Optional[Tuple[object, float]]:  # zt-mirror-served: the lock-free read path — ZT10 proves no aggregator-lock acquire can appear here
        """Serve ``key`` from the published epoch: ``(value, age_ms)``,
        or None on a miss (no snapshot, key not carried, or the age
        exceeds ``bound_ms``; ``bound_ms=None`` serves any age — the
        brownout cache-only posture). ``allow_stale=False`` restricts
        the serve to version-FRESH epochs: the store passes it for
        default requests on an uncontended lock, where an exact read is
        cheap and a within-bound stale answer would still surprise a
        caller that never opted into staleness (the same version
        reasoning that keeps ``_cached_read`` exact outside brownout)."""
        if not self.enabled:
            return None
        snap = self.snapshot()
        if snap is None or key not in snap.values:
            self.misses += 1
            return None
        fresh = snap.write_version == live_version
        age_ms = (
            0.0 if fresh
            else (time.monotonic() - snap.published_at) * 1000.0
        )
        if not fresh and not allow_stale:
            self.misses += 1
            return None
        if not fresh and bound_ms is not None and age_ms > bound_ms:
            self.misses += 1
            return None
        self.serves += 1
        if not fresh:
            self.stale_serves += 1
        self.serve_age_ms = age_ms
        if age_ms > self.serve_age_max_ms:
            self.serve_age_max_ms = age_ms
        ent = self._demand.get(key)  # GIL-atomic read; no lock
        if ent is not None:
            ent[1] = self.publishes  # keep served keys alive
        return (snap.values[key], age_ms)

    # -- publisher side (ticker thread / boot) ---------------------------

    def publish(self, force: bool = False, paced: bool = False) -> bool:
        """One epoch: lock once, run every demanded read program, swap.

        Skipped (returns False) when nothing could have changed — the
        aggregator's write_version still matches the published snapshot
        and no new demand key arrived — so an idle system never pulls
        the device at tick cadence just to republish identical bytes.

        ``paced=True`` (the ticker's call) additionally caps the
        publisher's lock duty cycle at 50%: a new epoch is refused
        until at least one last-publish-duration has elapsed since the
        previous one finished. On hardware where the read programs run
        in milliseconds the window is always long past at tick cadence;
        on a host where device reads run in seconds (CPU mesh, cold
        box) it is what stops back-to-back multi-second lock holds from
        convoying every fresh read and ingest tick behind the
        publisher. Explicit calls (boot, tests, benchmarks) stay
        unpaced.
        """
        if not self.enabled:
            return False
        agg = self._agg()
        if agg is None:
            return False
        if (
            paced and not force and self.last_publish_ms > 0.0
            and self._publish_done_at is not None
            and (time.monotonic() - self._publish_done_at) * 1000.0
            < self.last_publish_ms
        ):
            self.publish_backoffs += 1
            return False
        with self._demand_lock:
            entries = list(self._demand.items())
            dirty = self._dirty
            self._dirty = False
        version = getattr(agg, "write_version", 0)
        snap = self._snap
        if (
            not force and not dirty and snap is not None
            and snap.write_version == version
        ):
            self.publish_skips += 1
            return False
        t0 = time.perf_counter()
        values: Dict[str, object] = {}
        lock = getattr(agg, "lock", None)
        with querytrace.lock_label("mirror_publish"):
            # the ONE lock hold of the epoch; the read programs below
            # re-enter it (counted, never measured — an RLock re-acquire
            # by its holder cannot block)
            with (lock if lock is not None else nullcontext()):
                version = getattr(agg, "write_version", 0)
                for key, ent in entries:
                    try:
                        values[key] = ent[0]()
                    except Exception:
                        # one bad closure (e.g. a window that aged out)
                        # must not abort the epoch or kill the ticker
                        logger.exception(
                            "mirror publish: compute for %r failed", key
                        )
        publish_ms = (time.perf_counter() - t0) * 1000.0
        new = MirrorSnapshot(
            values=values,
            write_version=version,
            published_at=time.monotonic(),
            generation=self.gen + 2,
            publish_ms=publish_ms,
        )
        self.gen += 1   # odd: publish in progress
        self._snap = new
        self.gen += 1   # even: stable
        self.publishes += 1
        self.last_publish_ms = publish_ms
        self._publish_done_at = time.monotonic()
        self.publish_ms_sum += publish_ms
        obs.record("mirror_publish", publish_ms / 1000.0)
        sink = self.segment_sink
        if sink is not None:
            try:
                sink(new)
            except Exception:
                # the shm epoch lags one publish; in-process serving is
                # unaffected — never abort the epoch for the segment
                self.segment_sink_errors += 1
                logger.exception("mirror publish: segment sink failed")
        with self._demand_lock:
            for k, ent in list(self._demand.items()):
                if not ent[2] and (
                    self.publishes - ent[1] > self.DEMAND_TTL_PUBLISHES
                ):
                    del self._demand[k]
        return True

    def reset(self) -> None:
        """Drop the published snapshot (``store.clear()`` swaps the
        aggregator; its versions no longer compare). Demand and the
        ledger survive — the next publish refills from the new agg."""
        self.gen += 1
        self._snap = None
        self.gen += 1

    # -- observability ----------------------------------------------------

    def counters(self) -> Dict:
        """Flat gauges for ``ingest_counters`` → ``/metrics`` and the
        auto-rendered ``zipkin_tpu_mirror_*`` prometheus families."""
        snap = self.snapshot()
        return {
            "mirrorEnabled": int(self.enabled),
            "mirrorGeneration": self.gen,
            "mirrorPublishes": self.publishes,
            "mirrorPublishSkips": self.publish_skips,
            "mirrorPublishBackoffs": self.publish_backoffs,
            "mirrorPublishMs": round(self.last_publish_ms, 3),
            "mirrorPublishMsSum": round(self.publish_ms_sum, 3),
            "mirrorServes": self.serves,
            "mirrorStaleServes": self.stale_serves,
            "mirrorMisses": self.misses,
            "mirrorServeAgeMs": round(self.serve_age_ms, 3),
            "mirrorServeAgeMaxMs": round(self.serve_age_max_ms, 3),
            "mirrorKeys": len(snap.values) if snap is not None else 0,
            "mirrorDemandKeys": len(self._demand),
            "mirrorDemandOverflow": self.demand_overflow,
            "mirrorMaxStaleMs": self.max_stale_ms,
        }

    def status(self) -> Dict:
        """The ``/statusz`` mirror block: the flat ledger plus snapshot
        detail (carried keys, live age, the version it was cut at)."""
        body = dict(self.counters())
        snap = self.snapshot()
        if snap is not None:
            body["snapshot"] = {
                "generation": snap.generation,
                "writeVersion": snap.write_version,
                "ageMs": round(
                    (time.monotonic() - snap.published_at) * 1000.0, 3
                ),
                "publishMs": round(snap.publish_ms, 3),
                "keys": sorted(snap.values),
            }
        return body
