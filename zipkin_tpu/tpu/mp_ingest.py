"""Multi-process parse/pack tier feeding the device aggregator.

The reference scales ingest horizontally with N collector workers/nodes
(Kafka partition parallelism, ``KafkaCollector.java`` — SURVEY.md §2.8);
under CPython one process cannot: the r2 profile measured the device path
at ~490k spans/s/chip with the host parse GIL-serialized at ~231k
end-to-end, and a threaded feeder measured SLOWER (tpu/feeder.py). This
module is the multi-process analog the round-2 verdict ordered:

- **N parse workers** (``spawn``, never importing jax): raw JSON bytes ->
  native C parse + LOCAL vocab interning -> columnar pack -> trace-affine
  shard routing -> the packed 11-row wire image written into a shared-
  memory slot. Workers journal newly-interned strings per batch.
- **One dispatcher thread** (main process, owns the device): applies each
  worker's vocab journal to the GLOBAL vocab, remaps the image's packed
  service/key lanes worker-local -> global with three vectorized table
  lookups, then ``ingest_fused`` (device_put + jit step). Remapping is
  what lets workers intern lock-free: ids only need to be consistent
  per-worker, the journal replays them into one global id space.

Sampled archive parity: workers extract the same trace-affine 1/N span
slices the synchronous fast path archives (byte extents from the native
parser); the dispatcher re-decodes them with the reference codec, so
``/api/v2/trace/{id}`` serves identical spans whichever tier ingested.

On a single-core host this tier cannot beat the synchronous path (the
workers and the PJRT client time-slice one core — measured and recorded
in PROFILE_r03.md); it exists for multi-core hosts, where parse scales
with worker count while the dispatcher stays a thin device feeder.
"""

from __future__ import annotations

import itertools
import logging
import multiprocessing as mp
import queue
import threading
import time
from typing import List, Optional

import numpy as np

from zipkin_tpu import obs

logger = logging.getLogger(__name__)

# worker -> dispatcher message kinds
_KIND_BATCH = 0
_KIND_FALLBACK = 1
_KIND_EOF = 2


def _extract_archive_slices(parsed, every: int) -> List[bytes]:
    """The worker half of TpuStorage._archive_fast_sample: the exact JSON
    byte extents of the trace-affine 1/N sample (same hash rule, so the
    MP tier archives the same spans the sync path would)."""
    from zipkin_tpu.tpu.columnar import _mix32

    if every <= 0:
        return []
    n = parsed.n
    tid = parsed.tl0[:n] ^ parsed.tl1[:n] ^ parsed.th0[:n] ^ parsed.th1[:n]
    pick = np.nonzero(_mix32(tid) % np.uint32(every) == 0)[0]
    data = parsed.data
    off, ln = parsed.span_off, parsed.span_len
    return [bytes(data[off[i] : off[i] + ln[i]]) for i in pick]


def _worker_main(
    widx: int,
    work_q,
    result_q,
    shm_name: str,
    slot_bytes: int,
    slot_base: int,
    n_slots: int,
    slot_sem,
    params: dict,
) -> None:
    """Parse worker entry point (child process; numpy + C parser only —
    importing jax here would drag a PJRT client into every worker)."""
    from multiprocessing import shared_memory

    from zipkin_tpu import native
    from zipkin_tpu.native import PARSED_FIELDS
    from zipkin_tpu.tpu.archive import parsed_record
    from zipkin_tpu.tpu.columnar import Vocab, pack_parsed, route_fused

    shm = shared_memory.SharedMemory(name=shm_name)
    vocab = Vocab(params["max_services"], params["max_keys"])
    nvocab = native.NativeVocab(vocab) if native.available() else None
    n_shards = params["n_shards"]
    max_batch = params["max_batch"]
    pad = params["pad"]
    every = params["archive_every"]
    disk = params["archive_disk"]  # ship per-chunk raw records for the
    # disk archive (worker-LOCAL vocab ids; dispatcher remaps to global)
    boundary = params["sample_boundary"]  # None = keep everything
    # journal cursors: how much of the local vocab has been reported
    sent_svc, sent_name, sent_pair = 1, 1, 1
    slot_ids = itertools.cycle(range(n_slots))

    def handle(payload: bytes, state: dict) -> None:
        nonlocal sent_svc, sent_name, sent_pair
        parsed = (
            native.parse_spans(payload, nvocab=nvocab)
            if nvocab is not None
            else None
        )
        if parsed is None:
            # the strict-codec fallback needs Span objects: punt the
            # raw payload back to the dispatcher's slow path
            state["completed"] = True
            result_q.put((_KIND_FALLBACK, widx, payload))
            return
        nvocab.sync()
        n = parsed.n
        dropped = 0
        if boundary is not None and n:
            keep = native.sampler_keep(parsed, n, boundary)
            dropped = int(n - keep.sum())
            if dropped:
                idx = np.nonzero(keep)[0]
                for field in PARSED_FIELDS:
                    col = getattr(parsed, field, None)
                    if col is not None:
                        setattr(parsed, field, col[:n][idx])
                parsed.n = n = len(idx)
        if n == 0:
            state["completed"] = True
            result_q.put(
                (_KIND_BATCH, widx, None, None, 0, 0, 0, dropped,
                 [], [], [], [], (0, 0), None)
            )
            return
        for lo in range(0, n, max_batch):
            hi = min(lo + max_batch, n)
            if lo == 0 and hi == n:
                sub = parsed
            else:
                sub = native.ParsedColumns()
                sub.data = parsed.data
                for f in PARSED_FIELDS:
                    col = getattr(parsed, f, None)
                    setattr(sub, f, None if col is None else col[lo:hi])
                sub.n = hi - lo
            cols = pack_parsed(sub, vocab, pad)
            fused = route_fused(cols, n_shards)
            arch = _extract_archive_slices(sub, every)
            rec = parsed_record(sub) if disk else None
            # vocab journal since the last report (id order)
            svc_new = vocab.services._names[sent_svc:]
            name_new = vocab.span_names._names[sent_name:]
            pairs_new = vocab._key_list[sent_pair:]
            sent_svc += len(svc_new)
            sent_name += len(name_new)
            sent_pair += len(pairs_new)
            slot_sem.acquire()
            slot = next(slot_ids)
            dst = np.frombuffer(
                shm.buf, np.uint32, count=fused.size,
                offset=slot_base + slot * slot_bytes,
            )
            dst[:] = fused.reshape(-1)
            live_ts = cols.ts_min[cols.valid]
            ts_range = (
                (int(live_ts.min()), int(live_ts.max()))
                if live_ts.size
                else (0, 0)
            )
            # -1 marks a continuation chunk: the dispatcher decrements
            # inflight once per PAYLOAD, on the LAST chunk's message —
            # not the first, or drain() could return while later chunks
            # are still queued/being packed and miss spans the caller
            # was promised (ADVICE r3). The sampled-drop count rides the
            # completion chunk.
            is_last = hi == n
            state["shipped"] = True
            if is_last:
                state["completed"] = True
            result_q.put(
                (
                    _KIND_BATCH, widx, slot, fused.shape,
                    int(cols.valid.sum()),
                    int((cols.valid & cols.has_dur).sum()),
                    int((cols.valid & cols.err).sum()),
                    dropped if is_last else -1,
                    svc_new, name_new, pairs_new, arch, ts_range, rec,
                )
            )

    try:
        while True:
            item = work_q.get()
            if item is None:
                break
            state: dict = {"completed": False}
            try:
                handle(item, state)
            except Exception:  # pragma: no cover - keep the pool alive
                logging.getLogger(__name__).exception(
                    "mp-ingest worker %d failed on a payload", widx
                )
                if not state["completed"]:
                    if not state.get("shipped"):
                        # nothing reached the dispatcher: whole payload
                        # retries on the slow path
                        result_q.put((_KIND_FALLBACK, widx, item))
                    else:
                        # some chunks shipped without the completion
                        # marker — ship an empty completion record so
                        # inflight still decrements and drain() cannot
                        # hang. A fallback retry here would double-ingest
                        # the shipped chunks; the un-shipped tail is lost
                        # instead — logged above, bounded to one payload.
                        result_q.put(
                            (_KIND_BATCH, widx, None, None, 0, 0, 0, 0,
                             [], [], [], [], (0, 0), None)
                        )
    finally:
        result_q.put((_KIND_EOF, widx))
        shm.close()


class _IdMaps:
    """Worker-local -> global id tables, grown as journals arrive."""

    def __init__(self) -> None:
        self.svc = np.zeros(1, np.uint32)  # local id 0 -> global 0
        self.name = np.zeros(1, np.uint32)
        self.key = np.zeros(1, np.uint32)

    @staticmethod
    def _append(arr: np.ndarray, values: List[int]) -> np.ndarray:
        return np.concatenate([arr, np.asarray(values, np.uint32)]) if values else arr


class MultiProcessIngester:
    """Owns the worker pool + shared-memory slots + dispatcher thread.

    ``submit(payload)`` enqueues raw JSON v2 bytes and returns once the
    payload is accepted for processing (backpressure: the work queue is
    bounded). ``drain()`` blocks until everything submitted has reached
    the device. Parity with ``TpuStorage.ingest_json_fast`` — same
    sketches, same sampled archive — is asserted in
    tests/test_mp_ingest.py.
    """

    def __init__(
        self,
        store,
        workers: int = 2,
        slots_per_worker: int = 2,
        sampler=None,
        queue_depth: Optional[int] = None,
        metrics=None,
    ) -> None:
        from zipkin_tpu import native
        from zipkin_tpu.tpu.columnar import WIRE_ROWS

        if not native.available():
            raise RuntimeError("native codec unavailable; MP tier needs it")
        self.store = store
        self.workers = workers
        self._sampler = sampler
        agg = store.agg
        # worst case: every span of a max_batch chunk routes to one
        # shard, and route_fused rounds the per-shard lane count up to
        # its 256 pad multiple — slots must cover the ROUNDED bound or a
        # near-full chunk would write past its slot
        per_cap = ((store.max_batch + 255) // 256) * 256
        self._slot_bytes = agg.n_shards * WIRE_ROWS * per_cap * 4
        self._slots_per_worker = slots_per_worker
        ctx = mp.get_context("spawn")
        total = self._slot_bytes * slots_per_worker * workers
        from multiprocessing import shared_memory

        self._shm = shared_memory.SharedMemory(create=True, size=total)
        self._work_q = ctx.Queue(maxsize=queue_depth or 2 * workers)
        self._result_q = ctx.Queue()
        self._sems = [ctx.Semaphore(slots_per_worker) for _ in range(workers)]
        has_disk = getattr(store, "_disk", None) is not None
        params = dict(
            max_services=store.vocab.services.capacity,
            max_keys=store.vocab.max_keys,
            n_shards=agg.n_shards,
            max_batch=store.max_batch,
            pad=store._pad,
            # workers build per-chunk raw-archive records (payload +
            # index columns, worker-local ids) that the dispatcher
            # remaps and appends — the MP tier and the complete trace
            # store are no longer mutually exclusive (VERDICT r4 order
            # 2). The RAM 1/N sample then only matters for
            # autocompleteTags, exactly like the sync fast path.
            archive_disk=has_disk,
            archive_every=(
                store._fast_archive_every
                if (not has_disk or store.autocomplete_keys)
                else 0
            ),
            sample_boundary=(
                sampler._boundary
                if sampler is not None and sampler.rate < 1.0
                else None
            ),
        )
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(
                    w, self._work_q, self._result_q, self._shm.name,
                    self._slot_bytes,
                    w * slots_per_worker * self._slot_bytes,
                    slots_per_worker, self._sems[w], params,
                ),
                daemon=True,
            )
            for w in range(workers)
        ]
        for p in self._procs:
            p.start()
        self.metrics = metrics  # CollectorMetrics-shaped, optional
        self.counters = {"accepted": 0, "sampleDropped": 0, "fallbacks": 0}
        self._inflight = 0
        self._cv = threading.Condition()
        self._closed = False
        self._dispatch_error: Optional[BaseException] = None
        # reap reentrancy guard: _reap_dead_workers drains result_q via
        # _handle_msg, which can discover ANOTHER premature EOF — a
        # recursive reap would abort the outer one before its work-queue
        # salvage ran (ADVICE r4). Extra dead workers found mid-reap are
        # collected here and folded into the current reap instead.
        self._reaping = False
        self._reap_extra: List[int] = []
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="mp-ingest-dispatch", daemon=True
        )
        self._dispatcher.start()

    # -- producer side ---------------------------------------------------

    def submit(self, payload: bytes) -> None:
        if self._closed:
            raise RuntimeError("ingester closed")
        if self._dispatch_error is not None:
            raise RuntimeError("dispatcher died") from self._dispatch_error
        with self._cv:
            self._inflight += 1
        self._work_q.put(payload)

    def drain(self) -> None:
        """Block until every submitted payload has reached the device."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._inflight == 0 or self._dispatch_error is not None
            )
        if self._dispatch_error is not None:
            raise RuntimeError("dispatcher died") from self._dispatch_error
        # zt-lint: disable=ZT06 — drain's contract IS the blocking sync:
        # "until every payload has reached the device" means retire the
        # device queue, not just the dispatch threads
        self.store.agg.block_until_ready()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for _ in self._procs:
            # the work queue is bounded: with every worker dead (OOM
            # storm) and the queue full of acked payloads, a plain
            # put(None) would block forever. Only force space when
            # nothing can be consuming — a slow-but-alive pool keeps
            # its payloads.
            while True:
                try:
                    self._work_q.put(None, timeout=0.5)
                    break
                except queue.Full:
                    if self._dispatch_error is not None or not any(
                        p.is_alive() for p in self._procs
                    ):
                        try:
                            self._work_q.get_nowait()
                        except queue.Empty:
                            pass
        for p in self._procs:
            p.join(timeout=30)
            if p.is_alive():  # pragma: no cover - hang safety
                p.terminate()
        self._dispatcher.join(timeout=30)
        self._shm.close()
        try:
            self._shm.unlink()
        except FileNotFoundError:  # pragma: no cover
            pass

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        try:
            self._run_dispatch()
        except BaseException as e:
            logger.exception("mp-ingest dispatcher failed")
            self._dispatch_error = e
            with self._cv:
                self._cv.notify_all()
            self._sink_until_closed()

    def _sink_until_closed(self) -> None:
        """After a dispatcher failure, keep draining result_q and
        releasing shm slots so SURVIVING workers never wedge in
        slot_sem.acquire() with the only release site (the normal
        dispatch loop) gone — otherwise close() would burn its full join
        timeout per live worker and terminate() it mid-payload. Results
        are discarded: the error is already surfaced to submit()/drain(),
        so callers know batches after the failure point are lost."""
        while True:
            try:
                msg = self._result_q.get(timeout=0.25)
            except queue.Empty:
                if self._closed and not any(p.is_alive() for p in self._procs):
                    return
                continue
            if msg[0] == _KIND_BATCH and msg[2] is not None:
                self._sems[msg[1]].release()

    def _run_dispatch(self) -> None:
        import time

        maps = [_IdMaps() for _ in range(self.workers)]
        eof_set: set = set()
        last_liveness = time.monotonic()
        while len(eof_set) < self.workers:
            try:
                msg = self._result_q.get(timeout=0.5)
            except queue.Empty:
                if self._closed and not any(p.is_alive() for p in self._procs):
                    break
                if not self._closed:
                    self._check_liveness(maps, eof_set)
                    last_liveness = time.monotonic()
                continue
            self._handle_msg(msg, maps, eof_set)
            # liveness must ALSO run under sustained traffic: a busy
            # surviving worker keeps result_q non-empty, so the idle
            # branch alone could leave a dead worker's acked payloads
            # pinning _inflight for as long as load lasts
            if (
                not self._closed
                and time.monotonic() - last_liveness > 2.0
            ):
                self._check_liveness(maps, eof_set)
                last_liveness = time.monotonic()

    def _check_liveness(self, maps: List[_IdMaps], eof_set: set) -> None:
        """A worker that died uncleanly (segfault in the native parser,
        OOM kill) never sends EOF: without this check its inflight
        payloads would pin _inflight > 0 and drain()/stop() would wedge
        forever (ADVICE r3)."""
        dead = [
            w
            for w, p in enumerate(self._procs)
            if not p.is_alive() and w not in eof_set
        ]
        if dead:
            self._reap_dead_workers(dead, maps, eof_set)

    def _reap_dead_workers(
        self, dead: List[int], maps: List[_IdMaps], eof_set: set
    ) -> None:
        """A worker died without EOF. Recover what is recoverable, then
        surface a dispatcher error: results it already produced are
        applied, payloads still in the work queue re-dispatch on the
        slow path, but the payload it was processing is unaccountable
        (its chunk count is unknown), so drain() must raise rather than
        guess. Runs at most once per dispatcher lifetime (it ends in
        raise); further dead workers discovered while draining results
        below are folded into THIS reap via _reap_extra, never a nested
        reap that would abort the salvage pass (ADVICE r4)."""
        self._reaping = True
        # timeout-based drains, not get_nowait(): mp.Queue puts go
        # through a feeder thread, so a just-submitted payload can be
        # in the pipe but not yet visible — get_nowait() would miss it
        # and silently lose a 202-acked payload
        while True:  # apply results already produced (any worker)
            try:
                msg = self._result_q.get(timeout=0.25)
            except queue.Empty:
                break
            self._handle_msg(msg, maps, eof_set)
        if self._reap_extra:
            dead = dead + [w for w in self._reap_extra if w not in dead]
            self._reap_extra = []
        salvaged = 0
        # stop salvaging the moment close() starts: its shutdown
        # sentinels must reach the surviving workers, not this loop
        while not self._closed:  # payloads no dead worker will pick up
            try:
                payload = self._work_q.get(timeout=0.25)
            except queue.Empty:
                break
            if payload is None:
                # a concurrent close() raced us: try to hand the
                # sentinel back. put_nowait, never a blocking put — the
                # queue may have refilled, and blocking here would
                # deadlock shutdown. Dropping it on Full is safe by
                # COUNTING, not by any re-put mechanism: close() puts N
                # sentinels, this reap runs once per dispatcher lifetime
                # (it ends in raise) so at most 1 sentinel is dropped,
                # and >=1 worker is dead — N-1 sentinels still cover the
                # <=N-1 survivors. If reaping ever becomes repeatable,
                # this argument breaks and sentinels must be re-counted.
                try:
                    self._work_q.put_nowait(payload)
                except queue.Full:
                    pass
                break
            self._fallback(payload)
            self.counters["fallbacks"] += 1
            self._done_one()
            salvaged += 1
        with self._cv:
            unaccounted = self._inflight
        raise RuntimeError(
            f"mp-ingest worker(s) {dead} died uncleanly; "
            f"{salvaged} queued payload(s) salvaged via the slow path, "
            f"{unaccounted} acked payload(s) unaccounted (in-process at "
            "failure or raced by surviving workers) — restart the ingester"
        )

    def _handle_msg(self, msg, maps: List[_IdMaps], eof_set: set) -> None:
        store = self.store
        vocab = store.vocab
        kind = msg[0]
        if kind == _KIND_EOF:
            eof_set.add(msg[1])
            if not self._closed:
                # workers only EOF after close()'s None sentinel; an EOF
                # before close() means the worker loop was torn down by
                # a BaseException (KeyboardInterrupt, a failing
                # work_q.get) with its inflight payloads unaccounted —
                # without this, drain() would wedge with no error and
                # the liveness check would skip it (it IS in eof_set)
                if self._reaping:
                    # already inside a reap's result drain: fold this
                    # worker into the current reap instead of recursing
                    # (a nested reap would abort the outer salvage pass)
                    self._reap_extra.append(msg[1])
                else:
                    self._reap_dead_workers([msg[1]], maps, eof_set)
            return
        if kind == _KIND_FALLBACK:
            _, widx, payload = msg
            self._fallback(payload)
            self.counters["fallbacks"] += 1
            self._done_one()
            return
        (
            _, widx, slot, shape, n_spans, n_dur, n_err, dropped,
            svc_new, name_new, pairs_new, arch, ts_range, rec,
        ) = msg
        m = maps[widx]
        if svc_new or name_new or pairs_new:
            with store._intern_lock:
                m.svc = _IdMaps._append(
                    m.svc, [vocab.services.intern(s) for s in svc_new]
                )
                m.name = _IdMaps._append(
                    m.name, [vocab.span_names.intern(s) for s in name_new]
                )
                m.key = _IdMaps._append(
                    m.key,
                    [
                        vocab.key_id(int(m.svc[sl]), int(m.name[nl]))
                        for sl, nl in pairs_new
                    ],
                )
        if slot is not None:
            t0 = time.perf_counter()
            size = int(np.prod(shape))
            src = np.frombuffer(
                self._shm.buf, np.uint32, count=size,
                offset=widx * self._slots_per_worker * self._slot_bytes
                + slot * self._slot_bytes,
            )
            fused = src.reshape(shape).copy()
            self._sems[widx].release()  # slot free the moment we copied
            self._remap(fused, m)
            if arch:
                self._archive(arch)
            if rec is not None and getattr(store, "_disk", None) is not None:
                # remap the record's svc/rsvc/name/key lanes local ->
                # global (the journal above already covers every id this
                # chunk references) and append to the disk archive, so
                # MP-ingested traces are raw-archived exactly like the
                # sync fast path's (VERDICT r4 order 2)
                rec = list(rec)
                rec[7] = m.svc[rec[7]]
                rec[8] = m.svc[rec[8]]
                rec[9] = m.name[rec[9]]
                rec[10] = m.key[rec[10]]
                rec = tuple(rec)
                # sampling gate: the fused sketch feed below always sees
                # 100% of spans; only raw-archive retention is gated.
                # Gating happens AFTER the local->global remap so the
                # verdict's svc/rsvc indices address the published link
                # table, and here (not in disk_append_record) so the
                # sync fast path is not double-gated.
                sampler = store.agg.sampler
                if sampler is not None:
                    rec = sampler.gate_record(rec)
                if rec is not None:
                    store.disk_append_record(rec)
            store.agg.ingest_fused(
                fused, n_spans=n_spans, n_dur=n_dur, n_err=n_err,
                ts_range=ts_range,
            )
            obs.record("mp_record", time.perf_counter() - t0)
            self.counters["accepted"] += n_spans
        self.counters["sampleDropped"] += max(dropped, 0)
        if self.metrics is not None:
            self.metrics.increment_spans(n_spans + max(dropped, 0))
            if dropped > 0:
                self.metrics.increment_spans_dropped(dropped)
        # dropped == -1 marks a continuation chunk; inflight
        # decrements once per payload, on its LAST chunk's message
        if dropped >= 0:
            self._done_one()

    def _done_one(self) -> None:
        with self._cv:
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def _remap(self, fused: np.ndarray, m: _IdMaps) -> None:
        """Worker-local ids -> global ids, in place on the packed image
        (row 9 = svc<<16|rsvc, row 10 = key<<8|flags)."""
        sr = fused[:, 9, :]
        fused[:, 9, :] = (m.svc[sr >> 16] << np.uint32(16)) | m.svc[
            sr & np.uint32(0xFFFF)
        ]
        kf = fused[:, 10, :]
        fused[:, 10, :] = (m.key[kf >> 8] << np.uint32(8)) | (
            kf & np.uint32(0xFF)
        )

    def _archive(self, slices: List[bytes]) -> None:
        from zipkin_tpu.model import json_v2

        spans = []
        for raw in slices:
            try:
                spans.append(json_v2.decode_one_span(raw))
            except Exception:  # slice the strict codec rejects: skip
                continue
        if not spans:
            return
        sampler = self.store.agg.sampler
        if sampler is not None:
            # the RAM-archive sample is a retention surface like the disk
            # archive: gate it with the same verdicts (re-packing the few
            # 1-in-N sampled spans is cheap; interning is idempotent)
            from zipkin_tpu.tpu.columnar import pack_spans

            with self.store._intern_lock:
                cols = pack_spans(spans, self.store.vocab, 1)
            keep = sampler.verdict_cols(cols)[: len(spans)]
            spans = [s for s, k in zip(spans, keep) if k]
        if spans:
            self.store._archive.accept(spans).execute()

    def _fallback(self, payload: bytes) -> None:
        """Payloads the native parser rejects take the object path —
        including the boundary sampler, so a parser punt cannot smuggle
        unsampled spans into the store. Malformed payloads are counted
        and dropped (the asynchronous-ack trade: like the reference's
        Kafka collector, a poison message can't be HTTP-400'd after the
        202 — SURVEY.md §3.3)."""
        from zipkin_tpu.model import codec

        try:
            spans = codec.decode_spans(payload)
        except Exception:
            logger.warning("mp-ingest: undecodable payload dropped")
            if self.metrics is not None:
                self.metrics.increment_messages_dropped()
            return
        n_all = len(spans)
        if self._sampler is not None:
            spans = [s for s in spans if self._sampler.test(s)]
        self.store.accept(spans).execute()
        if self.metrics is not None:
            self.metrics.increment_spans(n_all)
            if n_all - len(spans):
                self.metrics.increment_spans_dropped(n_all - len(spans))
