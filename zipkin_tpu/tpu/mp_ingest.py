"""Multi-process parse/pack fan-out feeding the single dispatch core.

The reference scales ingest horizontally with N collector workers/nodes
(Kafka partition parallelism, ``KafkaCollector.java`` — SURVEY.md §2.8);
under CPython one process cannot: the r2 profile measured the device path
at ~490k spans/s/chip with the host parse GIL-serialized, and a threaded
feeder measured SLOWER (tpu/feeder.py). This module is the multi-process
fan-out tier (ISSUE 8), the collector's real fast path for both JSON v2
and proto3 payloads over HTTP and gRPC:

- **N parse workers** (``spawn``, never importing jax): raw JSON/proto3
  bytes -> native C parse + LOCAL vocab interning -> columnar pack ->
  trace-affine shard routing -> the packed 11-row wire image written into
  a shared-memory slot. Workers journal newly-interned strings per batch
  and ship their parse/pack/route wall time so the obs stage taxonomy
  covers the tier end-to-end.
- **One dispatcher thread** (main process, owns the device): applies each
  worker's vocab journal to the GLOBAL vocab, remaps the image's packed
  service/key lanes worker-local -> global with vectorized table lookups
  (``columnar.remap_fused``), then ``ingest_fused`` (device_put + jit
  step). WAL append and sampling verdicts ride ``ingest_fused`` on this
  side, so ack-after-durability semantics are bit-identical to the
  serial path. Remapping is what lets workers intern lock-free: ids only
  need to be consistent per-worker; the journal replays them into one
  global id space.

Backpressure contract: each worker owns a BOUNDED queue. ``submit(...,
block=False)`` — the server-boundary mode — raises
:class:`IngestBackpressure` when every live worker's queue is full; the
HTTP site maps it to 429 and the gRPC site to RESOURCE_EXHAUSTED so
senders back off instead of the tier buffering unboundedly. Since
ISSUE 13 the queue-full rejection is the LAST backpressure surface,
not the only one: the overload control plane (runtime/overload.py)
sheds bulk-class payloads at the collector boundary before they reach
these queues (B2/B3 brownout admission), tightens the sampling tier's
budget under sustained pressure, and stamps every rejection with
jittered backoff guidance (``Retry-After`` / ``retry-delay``).

Zero-loss worker death: the dispatcher retains every submitted payload
(``_pending``) until its results are APPLIED, and buffers per-payload
state mutations until the payload's completion chunk arrives. A worker
that dies mid-payload therefore loses nothing: its buffered chunks are
discarded (never applied, so no double-ingest) and every payload it
owned — queued or in-process — re-ingests on the slow path. The pool
keeps serving on the survivors; only a dead DISPATCHER (device failure)
surfaces as an error to submit()/drain().

Sampled archive parity: workers extract the same trace-affine 1/N span
slices the synchronous fast path archives (byte extents from the native
parser); the dispatcher re-decodes them with the reference codec
(format-sniffing, so proto3 payloads archive too), and
``/api/v2/trace/{id}`` serves identical spans whichever tier ingested.

On a single-core host this tier cannot beat the synchronous path (the
workers and the PJRT client time-slice one core — measured and recorded
in PROFILE_r03.md); it exists for multi-core hosts, where parse scales
with worker count while the dispatcher stays a thin device feeder.
"""

from __future__ import annotations

import itertools
import logging
import multiprocessing as mp
import queue
import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from zipkin_tpu import faults, obs
from zipkin_tpu.obs import critpath as _critpath

logger = logging.getLogger(__name__)

# worker -> dispatcher message kinds
_KIND_BATCH = 0
_KIND_FALLBACK = 1
_KIND_EOF = 2


class IngestBackpressure(RuntimeError):
    """The ingest tier refused a payload it could not absorb: every
    live parse worker's queue is full (``submit(..., block=False)``),
    the brownout ladder shed it (collector admission, ISSUE 13), or an
    injected allocation failure fired. The server boundary maps it to
    HTTP 429 / gRPC RESOURCE_EXHAUSTED — with the overload
    controller's jittered backoff guidance attached — so senders back
    off and retry instead of the tier buffering unboundedly."""


def _extract_archive_slices(parsed, every: int) -> List[bytes]:
    """The worker half of TpuStorage._archive_fast_sample: the exact raw
    byte extents of the trace-affine 1/N sample (same hash rule, so the
    MP tier archives the same spans the sync path would)."""
    from zipkin_tpu.tpu.columnar import _mix32

    if every <= 0:
        return []
    n = parsed.n
    tid = parsed.tl0[:n] ^ parsed.tl1[:n] ^ parsed.th0[:n] ^ parsed.th1[:n]
    pick = np.nonzero(_mix32(tid) % np.uint32(every) == 0)[0]
    data = parsed.data
    off, ln = parsed.span_off, parsed.span_len
    return [bytes(data[off[i] : off[i] + ln[i]]) for i in pick]


def _worker_main(
    widx: int,
    work_q,
    result_q,
    shm_name: str,
    slot_bytes: int,
    slot_base: int,
    n_slots: int,
    slot_sem,
    params: dict,
) -> None:
    """Parse worker entry point (child process; numpy + C parser only —
    importing jax here would drag a PJRT client into every worker)."""
    from multiprocessing import shared_memory

    from zipkin_tpu import native
    from zipkin_tpu.native import PARSED_FIELDS
    from zipkin_tpu.obs.critpath import (
        SEG_PACK,
        SEG_PARSE,
        SEG_ROUTE,
        SEG_SLOT_WAIT,
        CritPathWorkerView,
    )
    from zipkin_tpu.tpu.archive import parsed_record
    from zipkin_tpu.tpu.columnar import Vocab, pack_parsed, route_fused

    shm = shared_memory.SharedMemory(name=shm_name)
    cp_params = params.get("critpath")
    cview = (
        CritPathWorkerView(cp_params, widx) if cp_params is not None else None
    )
    vocab = Vocab(params["max_services"], params["max_keys"])
    nvocab = native.NativeVocab(vocab) if native.available() else None
    n_shards = params["n_shards"]
    max_batch = params["max_batch"]
    pad = params["pad"]
    every = params["archive_every"]
    disk = params["archive_disk"]  # ship per-chunk raw records for the
    # disk archive (worker-LOCAL vocab ids; dispatcher remaps to global)
    boundary = params["sample_boundary"]  # None = keep everything
    # journal cursors: how much of the local vocab has been reported
    sent_svc, sent_name, sent_pair = 1, 1, 1
    slot_ids = itertools.cycle(range(n_slots))

    def handle(pid: int, payload: bytes, state: dict, cslot: int) -> None:
        nonlocal sent_svc, sent_name, sent_pair
        traced = cview is not None and cslot >= 0
        if traced:
            # per-payload recalibration keeps the cross-process clock
            # bridge fresh; perf_counter floats convert losslessly to ns
            # at process-uptime magnitudes, so the stamps below reuse
            # the timestamps the stage timings already take
            cview.calibrate()
        t0 = time.perf_counter()
        # parse_spans sniffs the wire format: JSON v2 and proto3
        # ListOfSpans both land here, so the fan-out is format-agnostic
        parsed = (
            native.parse_spans(payload, nvocab=nvocab)
            if nvocab is not None
            else None
        )
        if parsed is None:
            # the strict-codec fallback needs Span objects: punt back to
            # the dispatcher, which still holds the payload bytes
            state["completed"] = True
            result_q.put((_KIND_FALLBACK, widx, pid))
            return
        nvocab.sync()
        n = parsed.n
        dropped = 0
        if boundary is not None and n:
            keep = native.sampler_keep(parsed, n, boundary)
            dropped = int(n - keep.sum())
            if dropped:
                idx = np.nonzero(keep)[0]
                for field in PARSED_FIELDS:
                    col = getattr(parsed, field, None)
                    if col is not None:
                        setattr(parsed, field, col[:n][idx])
                parsed.n = n = len(idx)
        parse_s = time.perf_counter() - t0
        if traced:
            cview.stamp(
                cslot, SEG_PARSE, int(t0 * 1e9),
                int((t0 + parse_s) * 1e9),
            )
        if n == 0:
            state["completed"] = True
            result_q.put(
                (_KIND_BATCH, widx, pid, None, None, 0, 0, 0, dropped,
                 [], [], [], [], (0, 0), None, parse_s, 0.0, 0.0)
            )
            return
        for lo in range(0, n, max_batch):
            hi = min(lo + max_batch, n)
            if lo == 0 and hi == n:
                sub = parsed
            else:
                sub = native.ParsedColumns()
                sub.data = parsed.data
                for f in PARSED_FIELDS:
                    col = getattr(parsed, f, None)
                    setattr(sub, f, None if col is None else col[lo:hi])
                sub.n = hi - lo
            t1 = time.perf_counter()
            cols = pack_parsed(sub, vocab, pad)
            t2 = time.perf_counter()
            fused = route_fused(cols, n_shards)
            route_s = time.perf_counter() - t2
            pack_s = t2 - t1
            arch = _extract_archive_slices(sub, every)
            rec = parsed_record(sub) if disk else None
            # vocab journal since the last report (id order)
            svc_new = vocab.services._names[sent_svc:]
            name_new = vocab.span_names._names[sent_name:]
            pairs_new = vocab._key_list[sent_pair:]
            sent_svc += len(svc_new)
            sent_name += len(name_new)
            sent_pair += len(pairs_new)
            ta = time.perf_counter()
            slot_sem.acquire()
            if traced:
                tb = time.perf_counter()
                cview.stamp(cslot, SEG_PACK, int(t1 * 1e9), int(t2 * 1e9))
                cview.stamp(
                    cslot, SEG_ROUTE, int(t2 * 1e9),
                    int((t2 + route_s) * 1e9),
                )
                cview.stamp(
                    cslot, SEG_SLOT_WAIT, int(ta * 1e9), int(tb * 1e9)
                )
            slot = next(slot_ids)
            dst = np.frombuffer(
                shm.buf, np.uint32, count=fused.size,
                offset=slot_base + slot * slot_bytes,
            )
            dst[:] = fused.reshape(-1)
            live_ts = cols.ts_min[cols.valid]
            ts_range = (
                (int(live_ts.min()), int(live_ts.max()))
                if live_ts.size
                else (0, 0)
            )
            # -1 marks a continuation chunk: the dispatcher completes a
            # payload (applies its buffered chunks, decrements inflight)
            # on the LAST chunk's message only, so drain() can never
            # return while later chunks are still queued or being packed
            # (ADVICE r3). The sampled-drop count and the parse timing
            # ride the completion chunk.
            is_last = hi == n
            if is_last:
                state["completed"] = True
            result_q.put(
                (
                    _KIND_BATCH, widx, pid, slot, fused.shape,
                    int(cols.valid.sum()),
                    int((cols.valid & cols.has_dur).sum()),
                    int((cols.valid & cols.err).sum()),
                    dropped if is_last else -1,
                    svc_new, name_new, pairs_new, arch, ts_range, rec,
                    parse_s if is_last else 0.0, pack_s, route_s,
                )
            )
            parse_s = 0.0  # only bill the parse once per payload

    try:
        while True:
            item = work_q.get()
            if item is None:
                break
            pid, payload, cslot = item
            state: dict = {"completed": False}
            try:
                handle(pid, payload, state, cslot)
            except Exception:  # pragma: no cover - keep the pool alive
                logging.getLogger(__name__).exception(
                    "mp-ingest worker %d failed on a payload", widx
                )
                if not state["completed"]:
                    # the dispatcher buffers chunk application until the
                    # completion marker, so any chunks this payload DID
                    # ship were never applied: a whole-payload fallback
                    # retry cannot double-ingest, and nothing is lost
                    result_q.put((_KIND_FALLBACK, widx, pid))
    finally:
        result_q.put((_KIND_EOF, widx))
        if cview is not None:
            cview.close()
        shm.close()


class _IdMaps:
    """Worker-local -> global id tables, grown as journals arrive."""

    def __init__(self) -> None:
        self.svc = np.zeros(1, np.uint32)  # local id 0 -> global 0
        self.name = np.zeros(1, np.uint32)
        self.key = np.zeros(1, np.uint32)

    @staticmethod
    def _append(arr: np.ndarray, values: List[int]) -> np.ndarray:
        return np.concatenate([arr, np.asarray(values, np.uint32)]) if values else arr


class MultiProcessIngester:
    """Owns the worker pool + shared-memory slots + dispatcher thread.

    ``submit(payload)`` enqueues raw JSON v2 / proto3 bytes onto one
    worker's bounded queue and returns once the payload is accepted.
    ``submit(payload, block=False)`` — the server boundary's mode —
    raises :class:`IngestBackpressure` instead of blocking when every
    live worker's queue is full. ``drain()`` blocks until everything
    submitted has reached the device. Parity with
    ``TpuStorage.ingest_json_fast`` — same sketches, same sampling
    verdicts, same WAL contents — is asserted in tests/test_mp_ingest.py
    and tests/test_fanout_parity.py.
    """

    def __init__(
        self,
        store,
        workers: int = 2,
        slots_per_worker: int = 2,
        sampler=None,
        queue_depth: Optional[int] = None,
        metrics=None,
        critpath_slots: int = 0,
        critpath_reclaim_s: float = 60.0,
    ) -> None:
        from zipkin_tpu import native
        from zipkin_tpu.tpu.columnar import WIRE_ROWS

        if not native.available():
            raise RuntimeError("native codec unavailable; MP tier needs it")
        self.store = store
        self.workers = workers
        self.queue_depth = queue_depth or 2  # PER-WORKER payload bound
        self._sampler = sampler
        agg = store.agg
        # worst case: every span of a max_batch chunk routes to one
        # shard, and route_fused rounds the per-shard lane count up to
        # its 256 pad multiple — slots must cover the ROUNDED bound or a
        # near-full chunk would write past its slot
        per_cap = ((store.max_batch + 255) // 256) * 256
        self._slot_bytes = agg.n_shards * WIRE_ROWS * per_cap * 4
        self._slots_per_worker = slots_per_worker
        ctx = mp.get_context("spawn")
        total = self._slot_bytes * slots_per_worker * workers
        from multiprocessing import shared_memory

        self._shm = shared_memory.SharedMemory(create=True, size=total)
        # one bounded queue per worker: backpressure is per-worker, and a
        # dead worker's queue can be salvaged without racing survivors
        self._work_qs = [
            ctx.Queue(maxsize=self.queue_depth) for _ in range(workers)
        ]
        self._result_q = ctx.Queue()
        self._sems = [ctx.Semaphore(slots_per_worker) for _ in range(workers)]
        has_disk = getattr(store, "_disk", None) is not None
        params = dict(
            max_services=store.vocab.services.capacity,
            max_keys=store.vocab.max_keys,
            n_shards=agg.n_shards,
            max_batch=store.max_batch,
            pad=store._pad,
            # workers build per-chunk raw-archive records (payload +
            # index columns, worker-local ids) that the dispatcher
            # remaps and appends — the MP tier and the complete trace
            # store are no longer mutually exclusive (VERDICT r4 order
            # 2). The RAM 1/N sample then only matters for
            # autocompleteTags, exactly like the sync fast path.
            archive_disk=has_disk,
            archive_every=(
                store._fast_archive_every
                if (not has_disk or store.autocomplete_keys)
                else 0
            ),
            sample_boundary=(
                sampler._boundary
                if sampler is not None and sampler.rate < 1.0
                else None
            ),
        )
        # critical-path interval ledger (obs/critpath.py): created before
        # the pool spawns so workers attach by name. The stitcher is
        # exposed as .critpath; the server registers it on the windows
        # ticker and the statusz/bench report reads its waterfall.
        self._cp_ledger = None
        self.critpath = None
        self._cslots: Dict[int, int] = {}
        if critpath_slots > 0:
            self._cp_ledger = _critpath.CritPathLedger(
                workers, critpath_slots
            )
            self.critpath = _critpath.CritPathStitcher(
                self._cp_ledger,
                queue_capacity=workers * self.queue_depth,
                recorder=obs.RECORDER,
                reclaim_age_s=critpath_reclaim_s,
            )
            params["critpath"] = self._cp_ledger.params()
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(
                    w, self._work_qs[w], self._result_q, self._shm.name,
                    self._slot_bytes,
                    w * slots_per_worker * self._slot_bytes,
                    slots_per_worker, self._sems[w], params,
                ),
                daemon=True,
            )
            for w in range(workers)
        ]
        for p in self._procs:
            p.start()
        self.metrics = metrics  # CollectorMetrics-shaped, optional
        # accuracy-observatory tap (obs/shadow.py): when attached, every
        # applied chunk's fused image is offered (O(1) bounded append —
        # the fused array is already this dispatcher's private copy)
        self.shadow = None
        self.counters = {
            "accepted": 0, "sampleDropped": 0, "fallbacks": 0, "rejected": 0,
        }
        # per-worker attribution (batch messages carry widx): a slow
        # worker is distinguishable from a slow pool. Mutated only on
        # the dispatcher thread; read lock-free by stats().
        self._wstats = [
            {"chunks": 0, "spans": 0, "payloads": 0, "parseUs": 0,
             "packUs": 0, "routeUs": 0, "fallbacks": 0}
            for _ in range(workers)
        ]
        # live per-worker occupancy (submitted minus finished) and its
        # high-water mark — the between-ticks saturation signal the
        # cumulative tallies above cannot show. Mutated under _cv.
        self._qdepth = [0] * workers
        self._qhigh = [0] * workers
        self._inflight = 0
        self._cv = threading.Condition()
        self._closed = False
        self._dispatch_error: Optional[BaseException] = None
        # payload retention until APPLIED (zero-loss worker death):
        # _pending maps payload id -> raw bytes, _assigned -> the worker
        # that owns it, _buffered -> its not-yet-applied chunk results.
        # _pending/_assigned are mutated by submit() (under _cv) and by
        # the dispatcher thread; _buffered only by the dispatcher.
        self._next_pid = 0
        self._rr = 0
        self._pending: Dict[int, bytes] = {}
        self._assigned: Dict[int, int] = {}
        self._buffered: Dict[int, list] = {}
        self._dead: Set[int] = set()
        self._maps: List[Optional[_IdMaps]] = [
            _IdMaps() for _ in range(workers)
        ]
        # reap reentrancy guard: _reap_dead_workers drains result_q via
        # _handle_msg, which can discover ANOTHER premature EOF — a
        # recursive reap would abort the outer one before its salvage
        # ran (ADVICE r4). Extra dead workers found mid-reap are
        # collected here and folded into the current reap instead.
        self._reaping = False
        self._reap_extra: List[int] = []
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="mp-ingest-dispatch", daemon=True
        )
        self._dispatcher.start()

    # -- producer side ---------------------------------------------------

    def submit(self, payload: bytes, *, block: bool = True) -> None:
        """Enqueue a payload onto one live worker's bounded queue.

        Registration happens BEFORE the queue put (under _cv, the same
        lock the reaper takes to mark workers dead), so a worker-death
        reap is linearized against submission: either the reap sees the
        registration and refeeds the payload, or submit() sees the
        worker marked dead and picks another.
        """
        while True:
            if self._closed:
                raise RuntimeError("ingester closed")
            if self._dispatch_error is not None:
                raise RuntimeError(
                    "dispatcher died"
                ) from self._dispatch_error
            with self._cv:
                live = [
                    w for w in range(self.workers) if w not in self._dead
                ]
                if not live:
                    raise RuntimeError(
                        "mp-ingest worker pool exhausted (every worker "
                        "died); restart the ingester"
                    )
                start = self._rr % len(live)
                self._rr += 1
                pid = self._next_pid
                self._next_pid += 1
                self._pending[pid] = payload
                self._inflight += 1
            wire_ns = (
                _critpath.WIRE_T0_NS.get()
                if self._cp_ledger is not None
                else 0
            )
            for w in live[start:] + live[:start]:
                with self._cv:
                    if w in self._dead:
                        continue
                    self._assigned[pid] = w
                cslot = -1
                if wire_ns:
                    t_en0 = time.perf_counter_ns()
                    cslot = self._cp_ledger.alloc(pid, w, wire_ns)
                    if cslot >= 0:
                        # stamp + register BEFORE the queue put: the
                        # dispatcher only writes this slot after the
                        # worker's result message, so main-side region
                        # writers stay causally serialized
                        self._cp_ledger.stamp(
                            cslot, _critpath.SEG_ENQUEUE, t_en0,
                            time.perf_counter_ns(), pid,
                        )
                        with self._cv:
                            self._cslots[pid] = cslot
                try:
                    self._work_qs[w].put_nowait((pid, payload, cslot))
                    with self._cv:
                        self._qdepth[w] += 1
                        if self._qdepth[w] > self._qhigh[w]:
                            self._qhigh[w] = self._qdepth[w]
                    return
                except queue.Full:
                    if cslot >= 0:
                        with self._cv:
                            self._cslots.pop(pid, None)
                        self._cp_ledger.abandon(cslot)
                    with self._cv:
                        if pid not in self._pending:
                            return  # a racing reap already refed it
                        if self._assigned.get(pid) == w:
                            self._assigned.pop(pid)
            # every live queue is full: roll the registration back
            with self._cv:
                if pid not in self._pending:
                    return  # a racing reap consumed it
                self._pending.pop(pid)
                self._assigned.pop(pid, None)
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()
            if not block:
                self.counters["rejected"] += 1
                raise IngestBackpressure(
                    f"every parse-worker queue is full "
                    f"({len(live)} workers x depth {self.queue_depth}); "
                    "retry after backoff"
                )
            time.sleep(0.002)

    def drain(self) -> None:
        """Block until every submitted payload has reached the device."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._inflight == 0 or self._dispatch_error is not None
            )
        if self._dispatch_error is not None:
            raise RuntimeError("dispatcher died") from self._dispatch_error
        # zt-lint: disable=ZT06 — drain's contract IS the blocking sync:
        # "until every payload has reached the device" means retire the
        # device queue, not just the dispatch threads
        self.store.agg.block_until_ready()

    def stats(self) -> dict:
        """Fan-out tier gauges, merged into TpuStorage.ingest_counters()
        so /metrics and /statusz show the tier."""
        with self._cv:
            inflight = self._inflight
            dead = len(self._dead)
            qdepth = list(self._qdepth)
            qhigh = list(self._qhigh)
        out = {
            "mpWorkers": self.workers,
            "mpWorkersAlive": self.workers - dead,
            "mpQueueDepth": self.queue_depth,
            "mpInflight": inflight,
            "mpAccepted": self.counters["accepted"],
            "mpSampleDropped": self.counters["sampleDropped"],
            "mpFallbacks": self.counters["fallbacks"],
            "mpRejected": self.counters["rejected"],
            # nested per-worker table — scalar-only consumers
            # (/prometheus gauge emission) skip non-scalar values
            "mpWorkerTable": [
                {"widx": w, "alive": w not in self._dead,
                 "queueDepth": qdepth[w], "queueHighWater": qhigh[w],
                 **dict(ws)}
                for w, ws in enumerate(self._wstats)
            ],
        }
        if self.critpath is not None:
            out.update(self.critpath.counters())
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for w, p in enumerate(self._procs):
            if w in self._dead:
                continue  # no consumer; nothing to shut down
            # per-worker bounded queue: a live worker keeps consuming,
            # so a timed put retried until it lands cannot hang; a
            # worker that died mid-shutdown just stops needing one
            while True:
                try:
                    self._work_qs[w].put(None, timeout=0.5)
                    break
                except queue.Full:
                    if not p.is_alive():
                        break
        for p in self._procs:
            p.join(timeout=30)
            if p.is_alive():  # pragma: no cover - hang safety
                p.terminate()
        self._dispatcher.join(timeout=30)
        for q in self._work_qs:
            # a dead worker's queue may still hold (already-salvaged)
            # payloads; don't let its feeder thread block interpreter
            # exit flushing them to a pipe nobody reads
            q.close()
            q.cancel_join_thread()
        if self._dispatch_error is not None:
            # the stored exception's traceback pins the _handle_msg
            # frame, whose locals include an ndarray VIEW into a shm
            # slot — shm.close() would refuse ("exported pointers
            # exist"). The dispatcher thread is joined, so the frames
            # are safe to clear; drain()'s re-raise keeps the message.
            import traceback

            tb = self._dispatch_error.__traceback__
            if tb is not None:
                traceback.clear_frames(tb)
        self._buffered.clear()
        self._shm.close()
        try:
            self._shm.unlink()
        except FileNotFoundError:  # pragma: no cover
            pass
        if self._cp_ledger is not None:
            self._cp_ledger.close()

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        try:
            self._run_dispatch()
        except BaseException as e:
            logger.exception("mp-ingest dispatcher failed")
            self._dispatch_error = e
            with self._cv:
                self._cv.notify_all()
            self._sink_until_closed()

    def _sink_until_closed(self) -> None:
        """After a dispatcher failure, keep draining result_q and
        releasing shm slots so SURVIVING workers never wedge in
        slot_sem.acquire() with the only release site (the normal
        dispatch loop) gone — otherwise close() would burn its full join
        timeout per live worker and terminate() it mid-payload. Results
        are discarded: the error is already surfaced to submit()/drain(),
        so callers know batches after the failure point are lost."""
        while True:
            try:
                msg = self._result_q.get(timeout=0.25)
            except queue.Empty:
                if self._closed and not any(p.is_alive() for p in self._procs):
                    return
                continue
            if msg[0] == _KIND_BATCH and msg[3] is not None:
                self._sems[msg[1]].release()

    def _run_dispatch(self) -> None:
        eof_set: set = set()
        last_liveness = time.monotonic()
        while len(eof_set) < self.workers:
            try:
                msg = self._result_q.get(timeout=0.5)
            except queue.Empty:
                if self._closed and not any(p.is_alive() for p in self._procs):
                    break
                if not self._closed:
                    self._check_liveness(eof_set)
                    last_liveness = time.monotonic()
                continue
            self._handle_msg(msg, eof_set)
            # liveness must ALSO run under sustained traffic: a busy
            # surviving worker keeps result_q non-empty, so the idle
            # branch alone could leave a dead worker's acked payloads
            # pinning _inflight for as long as load lasts
            if (
                not self._closed
                and time.monotonic() - last_liveness > 2.0
            ):
                self._check_liveness(eof_set)
                last_liveness = time.monotonic()

    def _check_liveness(self, eof_set: set) -> None:
        """A worker that died uncleanly (segfault in the native parser,
        OOM kill) never sends EOF: without this check its inflight
        payloads would pin _inflight > 0 and drain()/stop() would wedge
        forever (ADVICE r3)."""
        dead = [
            w
            for w, p in enumerate(self._procs)
            if not p.is_alive() and w not in eof_set
        ]
        if dead:
            self._reap_dead_workers(dead, eof_set)

    def _reap_dead_workers(self, dead: List[int], eof_set: set) -> None:
        """A worker died without EOF. Recover EVERYTHING and keep the
        pool serving on the survivors: because chunk application is
        buffered until a payload's completion marker, a half-processed
        payload has mutated no store state — its buffered chunks are
        discarded and the whole payload (plus everything queued behind
        it) re-ingests on the slow path. Zero acked-span loss, no
        double-ingest, and the dead worker's _IdMaps / inflight
        accounting are released (the leak the r8 satellite named).
        Re-entrancy: draining result_q below can discover ANOTHER
        premature EOF — those fold into THIS reap via _reap_extra
        rather than recursing (ADVICE r4)."""
        self._reaping = True
        try:
            # mark dead under _cv FIRST: submit() registers under the
            # same lock, so after this no new payload can target these
            # workers, and every already-registered one is visible to
            # the refeed scan below
            with self._cv:
                self._dead.update(dead)
            # timeout-based drains, not get_nowait(): mp.Queue puts go
            # through a feeder thread, so a just-shipped result can be
            # in the pipe but not yet visible — get_nowait() would miss
            # chunks a surviving worker already produced
            while True:  # apply results already produced (any worker)
                try:
                    msg = self._result_q.get(timeout=0.25)
                except queue.Empty:
                    break
                self._handle_msg(msg, eof_set)
            if self._reap_extra:
                with self._cv:
                    self._dead.update(self._reap_extra)
                dead = dead + [w for w in self._reap_extra if w not in dead]
                self._reap_extra = []
            refed = 0
            for w in dead:
                eof_set.add(w)
                self._maps[w] = None  # free the dead worker's id tables
                # empty its queue so the feeder thread can't block
                # shutdown; the payloads themselves re-ingest via the
                # _assigned scan (they are all still in _pending)
                while True:
                    try:
                        item = self._work_qs[w].get(timeout=0.25)
                    except queue.Empty:
                        break
                    del item
                with self._cv:
                    owned = [
                        p for p, a in self._assigned.items() if a == w
                    ]
                for pid in owned:
                    self._buffered.pop(pid, None)
                    payload = self._pending.get(pid)
                    if payload is None:
                        continue
                    # the dead worker's ledger slots would stay OPEN
                    # forever: recycle them now (no stuck timelines)
                    self._drop_cslot(pid)
                    self._fallback(payload)
                    self.counters["fallbacks"] += 1
                    self._finish(pid)
                    refed += 1
        finally:
            self._reaping = False
        logger.warning(
            "mp-ingest worker(s) %s died uncleanly; %d acked payload(s) "
            "re-ingested via the slow path, pool continues on %d "
            "survivor(s)",
            dead, refed, self.workers - len(self._dead),
        )

    def _handle_msg(self, msg, eof_set: set) -> None:  # zt-dispatch-critical: single thread between N workers and the device
        store = self.store
        vocab = store.vocab
        kind = msg[0]
        if kind == _KIND_EOF:
            eof_set.add(msg[1])
            if not self._closed:
                # workers only EOF after close()'s None sentinel; an EOF
                # before close() means the worker loop was torn down by
                # a BaseException (KeyboardInterrupt, a failing
                # work_q.get) with its inflight payloads unaccounted —
                # treat it exactly like an unclean death and refeed
                if self._reaping:
                    self._reap_extra.append(msg[1])
                else:
                    self._reap_dead_workers([msg[1]], eof_set)
            return
        if kind == _KIND_FALLBACK:
            _, widx, pid = msg
            payload = self._pending.get(pid)
            if payload is None:
                return  # a reap already refed it
            self._buffered.pop(pid, None)
            self._drop_cslot(pid)  # slow-path retry: timeline abandoned
            self._fallback(payload)
            self.counters["fallbacks"] += 1
            if 0 <= widx < len(self._wstats):
                self._wstats[widx]["fallbacks"] += 1
            self._finish(pid)
            return
        (
            _, widx, pid, slot, shape, n_spans, n_dur, n_err, dropped,
            svc_new, name_new, pairs_new, arch, ts_range, rec,
            parse_s, pack_s, route_s,
        ) = msg
        if widx in self._dead or pid not in self._pending:
            # late chunk from a reaped worker (its payload already
            # re-ingested on the slow path): only the slot needs freeing
            if slot is not None:
                self._sems[widx].release()
            return
        m = self._maps[widx]
        cs = self._cslots.get(pid, -1) if self._cp_ledger is not None else -1
        if svc_new or name_new or pairs_new:
            tv0 = time.perf_counter()
            with store._intern_lock:
                # zt-lint: disable=ZT09 — journal replay is per NEWLY
                # INTERNED STRING (bounded by vocab capacity, amortized
                # zero per span), not per span
                m.svc = _IdMaps._append(
                    m.svc, [vocab.services.intern(s) for s in svc_new]
                )
                # zt-lint: disable=ZT09 — per new string, as above
                m.name = _IdMaps._append(
                    m.name, [vocab.span_names.intern(s) for s in name_new]
                )
                # zt-lint: disable=ZT09 — per new (svc, name) pair
                m.key = _IdMaps._append(
                    m.key,
                    [
                        vocab.key_id(int(m.svc[sl]), int(m.name[nl]))
                        for sl, nl in pairs_new
                    ],
                )
            tv1 = time.perf_counter()
            obs.record("mp_vocab_replay", tv1 - tv0)
            if cs >= 0:
                self._cp_ledger.stamp(
                    cs, _critpath.SEG_VOCAB_REPLAY,
                    int(tv0 * 1e9), int(tv1 * 1e9), pid,
                )
        # worker-measured stage wall time: the workers can't touch the
        # in-process flight recorder, so their parse/pack/route timings
        # ride the batch message and are recorded here. record_relayed
        # (histogram-only): the time was spent in a worker process, so a
        # budget crossing must not emit a self-span B3-linked to
        # whatever request context this dispatcher thread holds.
        if parse_s > 0.0:
            obs.record_relayed("parse", parse_s)
        if pack_s > 0.0:
            obs.record_relayed("pack", pack_s)
        if route_s > 0.0:
            obs.record_relayed("route", route_s)
        ws = self._wstats[widx]
        ws["chunks"] += 1
        ws["spans"] += n_spans
        ws["parseUs"] += int(parse_s * 1e6 + 0.5)
        ws["packUs"] += int(pack_s * 1e6 + 0.5)
        ws["routeUs"] += int(route_s * 1e6 + 0.5)
        if dropped >= 0:
            ws["payloads"] += 1
        if slot is not None:
            t0 = time.perf_counter()
            size = int(np.prod(shape))
            src = np.frombuffer(
                self._shm.buf, np.uint32, count=size,
                offset=widx * self._slots_per_worker * self._slot_bytes
                + slot * self._slot_bytes,
            )
            fused = src.reshape(shape).copy()
            self._sems[widx].release()  # slot free the moment we copied
            tc1 = time.perf_counter()
            obs.record("mp_shm_copy", tc1 - t0)
            if cs >= 0:
                self._cp_ledger.stamp(
                    cs, _critpath.SEG_SHM_COPY,
                    int(t0 * 1e9), int(tc1 * 1e9), pid,
                )
            from zipkin_tpu.tpu.columnar import remap_fused

            remap_fused(fused, m.svc, m.key)
            tr1 = time.perf_counter()
            obs.record("mp_lut_remap", tr1 - tc1)
            if cs >= 0:
                self._cp_ledger.stamp(
                    cs, _critpath.SEG_LUT_REMAP,
                    int(tc1 * 1e9), int(tr1 * 1e9), pid,
                )
            if rec is not None:
                # remap the record's svc/rsvc/name/key lanes local ->
                # global NOW (the journal above covers every id this
                # chunk references; the maps may have grown by apply
                # time); append is deferred to the completion flush
                rec = list(rec)
                rec[7] = m.svc[rec[7]]
                rec[8] = m.svc[rec[8]]
                rec[9] = m.name[rec[9]]
                rec[10] = m.key[rec[10]]
                rec = tuple(rec)
            self._buffered.setdefault(pid, []).append(
                (fused, n_spans, n_dur, n_err, ts_range, arch, rec,
                 time.perf_counter() - t0)
            )
        # dropped == -1 marks a continuation chunk; the payload is
        # applied atomically on its LAST chunk's message
        if dropped >= 0:
            self._flush_payload(pid, dropped)

    def _flush_payload(self, pid: int, dropped: int) -> None:  # zt-dispatch-critical: applies a completed payload to the device + durability path
        """Apply a completed payload's buffered chunks: RAM/disk archive,
        then ingest_fused — whose dispatch side carries the WAL append
        and sampling verdicts, preserving ack-after-durability exactly
        like the serial path. Until this runs, the payload has mutated
        nothing, which is what makes worker death recoverable."""
        store = self.store
        total = 0
        t0 = time.perf_counter()
        copy_s = 0.0
        cs = self._cslots.get(pid, -1) if self._cp_ledger is not None else -1
        if cs >= 0:
            # arm the thread-local so wal.py's append/fsync stamps land
            # in this payload's timeline (the WAL rides ingest_fused)
            _critpath.set_active(self._cp_ledger, cs, pid)
        # zt-lint: disable=ZT09 — per CHUNK (max_batch-sized), not per
        # span; all per-span work inside is vectorized
        for fused, n_spans, n_dur, n_err, ts_range, arch, rec, c_s in (
            self._buffered.pop(pid, ())
        ):
            copy_s += c_s
            if arch:
                self._archive(arch)
            if rec is not None and getattr(store, "_disk", None) is not None:
                # sampling gate: the fused sketch feed below always sees
                # 100% of spans; only raw-archive retention is gated.
                # Gating happens here (not in disk_append_record) so the
                # sync fast path is not double-gated, and at flush time
                # so verdicts see the same publish state as the serial
                # path's dispatch-ordered gate.
                sampler = store.agg.sampler
                if sampler is not None:
                    rec = sampler.gate_record(rec)
                if rec is not None:
                    store.disk_append_record(rec)
            if self.shadow is not None:
                self.shadow.offer_fused(fused)
            tf0 = time.perf_counter()
            # resource-fault injection (faults.py, ISSUE 13): an armed
            # feed.latency site sleeps here — the exact seam where a
            # slow device feed stalls the dispatcher — so overload
            # tests can manufacture queue saturation deterministically
            faults.resource_point("feed.latency")
            store.agg.ingest_fused(
                fused, n_spans=n_spans, n_dur=n_dur, n_err=n_err,
                ts_range=ts_range,
            )
            tf1 = time.perf_counter()
            obs.record("mp_device_feed", tf1 - tf0)
            if cs >= 0:
                self._cp_ledger.stamp(
                    cs, _critpath.SEG_DEVICE_FEED,
                    int(tf0 * 1e9), int(tf1 * 1e9), pid,
                )
            total += n_spans
        if cs >= 0:
            _critpath.clear_active()
        obs.record("mp_record", copy_s + (time.perf_counter() - t0))
        self.counters["accepted"] += total
        self.counters["sampleDropped"] += max(dropped, 0)
        if self.metrics is not None:
            self.metrics.increment_spans(total + max(dropped, 0))
            if dropped > 0:
                self.metrics.increment_spans_dropped(dropped)
        if cs >= 0:
            # durable ack: the WAL append + device feed above completed
            self._cp_ledger.ack(cs, pid)
        self._finish(pid)

    def _drop_cslot(self, pid: int) -> None:
        """Abandon a payload's timeline (fallback/reap path): partial
        stamps would decompose misleadingly, so the slot recycles now."""
        if self._cp_ledger is None:
            return
        with self._cv:
            cs = self._cslots.pop(pid, -1)
        if cs >= 0:
            self._cp_ledger.abandon(cs)

    def _finish(self, pid: int) -> None:
        with self._cv:
            self._pending.pop(pid, None)
            w = self._assigned.pop(pid, None)
            self._cslots.pop(pid, None)
            if w is not None and self._qdepth[w] > 0:
                self._qdepth[w] -= 1
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def _archive(self, slices: List[bytes]) -> None:
        from zipkin_tpu.tpu.store import _decode_raw_span

        spans = []
        for raw in slices:
            try:
                spans.append(_decode_raw_span(raw))
            except Exception:  # slice the strict codec rejects: skip
                continue
        if not spans:
            return
        sampler = self.store.agg.sampler
        if sampler is not None:
            # the RAM-archive sample is a retention surface like the disk
            # archive: gate it with the same verdicts (re-packing the few
            # 1-in-N sampled spans is cheap; interning is idempotent)
            from zipkin_tpu.tpu.columnar import pack_spans

            with self.store._intern_lock:
                cols = pack_spans(spans, self.store.vocab, 1)
            keep = sampler.verdict_cols(cols)[: len(spans)]
            spans = [s for s, k in zip(spans, keep) if k]
        if spans:
            self.store._archive.accept(spans).execute()

    def _fallback(self, payload: bytes) -> None:
        """Payloads the native parser rejects — or that a dead worker
        owned — take the object path, including the boundary sampler, so
        a parser punt cannot smuggle unsampled spans into the store.
        Malformed payloads are counted and dropped (the asynchronous-ack
        trade: like the reference's Kafka collector, a poison message
        can't be HTTP-400'd after the 202 — SURVEY.md §3.3). The codec
        sniffs the wire format, so proto3 payloads fall back too."""
        from zipkin_tpu.model import codec

        try:
            spans = codec.decode_spans(payload)
        except Exception:
            logger.warning("mp-ingest: undecodable payload dropped")
            if self.metrics is not None:
                self.metrics.increment_messages_dropped()
            return
        n_all = len(spans)
        if self._sampler is not None:
            spans = [s for s in spans if self._sampler.test(s)]
        self.store.accept(spans).execute()
        if self.metrics is not None:
            self.metrics.increment_spans(n_all)
            if n_all - len(spans):
                self.metrics.increment_spans_dropped(n_all - len(spans))
