"""Multi-process parse/pack fan-out feeding the single dispatch core.

The reference scales ingest horizontally with N collector workers/nodes
(Kafka partition parallelism, ``KafkaCollector.java`` — SURVEY.md §2.8);
under CPython one process cannot: the r2 profile measured the device path
at ~490k spans/s/chip with the host parse GIL-serialized, and a threaded
feeder measured SLOWER (tpu/feeder.py). This module is the multi-process
fan-out tier (ISSUE 8, rebuilt around the span ring in ISSUE 16), the
collector's real fast path for both JSON v2 and proto3 payloads over
HTTP and gRPC:

- **N parse workers** (``spawn``, never importing jax): raw JSON/proto3
  bytes -> native C parse + LOCAL vocab interning -> columnar pack ->
  trace-affine shard routing -> the packed 11-row wire image written
  straight into a **shared-memory span-ring slot** (tpu/ring.py)
  together with the chunk's pickled sidecar (vocab journal, archive
  slices, disk record). No per-chunk metadata message, no pickling of
  the image: publishing a slot is a handful of word stores behind a
  seqlock generation, and the per-worker stripe makes the handoff
  lock-free in both directions.
- **One dispatcher thread** (main process, owns the device): drains
  contiguous runs of READY slots per stripe, replays each chunk's vocab
  journal into the GLOBAL vocab, then flushes completed payloads in
  **coalesced groups**: up to ``coalesce_max`` chunks (bounded by the
  aggregator's lane cap) become ONE ``concat_remap`` gather into a
  bucket-padded image + ONE jitted ingest step + ONE WAL record, acked
  together — amortizing the ~16 µs/span per-chunk dispatch overhead
  INGEST_r08 measured. The chunk image is consumed as a zero-copy view
  into its ring slot; the coalesce gather (or, at ``coalesce_max=1``,
  the same per-chunk copy+remap as before) is the only copy it takes.
  WAL append and sampling verdicts ride ``ingest_fused`` on this side,
  so ack-after-durability semantics are bit-identical to the serial
  path. Remapping is what lets workers intern lock-free: ids only need
  to be consistent per-worker; the journal replays them into one global
  id space.

Ordering across the two channels (ring slots for images, the result
queue for oversized sidecars / strict-codec punts / EOF) is pinned by a
per-worker chunk sequence number: the dispatcher applies a worker's
chunks strictly in ``wseq`` order, holding back whichever channel runs
ahead, so a payload's chunks — and its vocab-journal deltas — replay in
exactly the order the worker produced them.

Backpressure contract: ring occupancy is the tier's backpressure basis.
A full stripe stalls its worker's blocking ``claim()``, the stalled
worker stops pulling from its bounded delivery queue, and the queue
fills — so ring congestion propagates to the submit boundary without
ever rejecting while a queue slot is free (routing merely PREFERS
workers with stripe headroom). ``submit(..., block=False)`` — the
server-boundary mode — raises :class:`IngestBackpressure` only when
every live worker's delivery queue is full. The HTTP site maps it to 429
and the gRPC site to RESOURCE_EXHAUSTED so senders back off instead of
the tier buffering unboundedly. Since ISSUE 13 that rejection is the
LAST backpressure surface, not the only one: the overload control plane
(runtime/overload.py) sheds at the collector boundary first — per-tenant
budget sheds (scope ``tenant``: one flooding tenant is limited while
everyone else rides B0) and then global B2/B3 brownout admission (scope
``global``) — tightens the sampling tier's budget under sustained
pressure, and stamps every rejection with backoff guidance AND its
shedding scope (``Retry-After`` / ``X-Shed-Scope`` on HTTP,
``retry-delay`` / ``shed-scope`` gRPC trailers). A saturation rejection
from this tier is a global-scope shed: every tenant's traffic funnels
through the same worker queues.

Zero-loss worker death: the dispatcher retains every submitted payload
(``_pending``) until its results are APPLIED, and buffers per-payload
state mutations until the payload's completion chunk arrives. A worker
that dies mid-payload therefore loses nothing: its ring stripe is
reclaimed (published-but-unconsumed slots discarded, the torn
mid-write slot a SIGKILL leaves reset via the pid guard), its buffered
chunks are discarded (never applied, so no double-ingest) and every
payload it owned — queued or in-process — re-ingests on the slow path.
The pool keeps serving on the survivors; only a dead DISPATCHER (device
failure) surfaces as an error to submit()/drain().

Sampled archive parity: workers extract the same trace-affine 1/N span
slices the synchronous fast path archives (byte extents from the native
parser); the dispatcher re-decodes them with the reference codec
(format-sniffing, so proto3 payloads archive too), and
``/api/v2/trace/{id}`` serves identical spans whichever tier ingested.

On a single-core host this tier cannot beat the synchronous path (the
workers and the PJRT client time-slice one core — measured and recorded
in PROFILE_r03.md); it exists for multi-core hosts, where parse scales
with worker count while the dispatcher stays a thin device feeder.
"""

from __future__ import annotations

import logging
import multiprocessing as mp
import queue
import threading
import time
from typing import Dict, List, Optional, Set

import numpy as np

from zipkin_tpu import faults, obs
from zipkin_tpu.obs import critpath as _critpath
from zipkin_tpu.tpu import ring as ring_mod

logger = logging.getLogger(__name__)

# worker -> dispatcher result-queue message kinds. Chunk IMAGES travel
# through the span ring; the queue carries only what cannot ride a
# bounded slot (oversized sidecars, empty-payload completions), the
# strict-codec punts, and EOF.
_KIND_BATCH = 0      # (kind, widx, pid, wseq, fused|None, n_spans, n_dur,
#                       n_err, dropped, svc_new, name_new, pairs_new,
#                       arch, ts_range, rec, parse_s, pack_s, route_s)
_KIND_FALLBACK = 1   # (kind, widx, pid, wseq)
_KIND_EOF = 2        # (kind, widx)
_KIND_NUDGE = 3      # (kind,) — wakeup only: a ring slot was published


class IngestBackpressure(RuntimeError):
    """The ingest tier refused a payload it could not absorb: every
    live parse worker's delivery queue is full — each backed up behind
    a congested ring stripe or a busy worker — in
    ``submit(..., block=False)``, the admission chokepoint shed it
    (per-tenant budget or global brownout ladder, ISSUEs 13/18), or an
    injected allocation failure fired. The server boundary maps it to
    HTTP 429 / gRPC RESOURCE_EXHAUSTED — with backoff guidance and the
    shedding ``scope`` attached, so a client can tell "you are being
    limited" (scope ``tenant``, guidance from that tenant's own budget)
    from "the system is browning out" (scope ``global``, guidance from
    the load index) — and senders back off and retry instead of the
    tier buffering unboundedly."""

    def __init__(self, msg: str = "", *, scope: str = "global",
                 tenant: Optional[str] = None,
                 retry_after_s: Optional[float] = None) -> None:
        super().__init__(msg)
        self.scope = scope
        self.tenant = tenant
        self.retry_after_s = retry_after_s


def _extract_archive_slices(parsed, every: int) -> List[bytes]:
    """The worker half of TpuStorage._archive_fast_sample: the exact raw
    byte extents of the trace-affine 1/N sample (same hash rule, so the
    MP tier archives the same spans the sync path would)."""
    from zipkin_tpu.tpu.columnar import _mix32

    if every <= 0:
        return []
    n = parsed.n
    tid = parsed.tl0[:n] ^ parsed.tl1[:n] ^ parsed.th0[:n] ^ parsed.th1[:n]
    pick = np.nonzero(_mix32(tid) % np.uint32(every) == 0)[0]
    data = parsed.data
    off, ln = parsed.span_off, parsed.span_len
    return [bytes(data[off[i] : off[i] + ln[i]]) for i in pick]


def _worker_main(
    widx: int,
    work_q,
    result_q,
    ring_params: dict,
    params: dict,
) -> None:
    """Parse worker entry point (child process; numpy + C parser only —
    importing jax here would drag a PJRT client into every worker)."""
    from zipkin_tpu import native
    from zipkin_tpu.native import PARSED_FIELDS
    from zipkin_tpu.obs.critpath import (
        SEG_PACK,
        SEG_PARSE,
        SEG_RING_WAIT,
        SEG_ROUTE,
        CritPathWorkerView,
    )
    from zipkin_tpu.tpu.archive import parsed_record
    from zipkin_tpu.tpu.columnar import Vocab, pack_parsed, route_fused
    from zipkin_tpu.tpu.ring import RingProducer, pack_aux

    prod = RingProducer(ring_params, widx)
    cp_params = params.get("critpath")
    cview = (
        CritPathWorkerView(cp_params, widx) if cp_params is not None else None
    )
    vocab = Vocab(params["max_services"], params["max_keys"])
    nvocab = native.NativeVocab(vocab) if native.available() else None
    n_shards = params["n_shards"]
    max_batch = params["max_batch"]
    pad = params["pad"]
    every = params["archive_every"]
    disk = params["archive_disk"]  # ship per-chunk raw records for the
    # disk archive (worker-LOCAL vocab ids; dispatcher remaps to global)
    boundary = params["sample_boundary"]  # None = keep everything
    # journal cursors: how much of the local vocab has been reported
    sent_svc, sent_name, sent_pair = 1, 1, 1

    def handle(pid: int, payload: bytes, state: dict, cslot: int,
               tidx: int) -> None:
        nonlocal sent_svc, sent_name, sent_pair
        traced = cview is not None and cslot >= 0
        if traced:
            # per-payload recalibration keeps the cross-process clock
            # bridge fresh; perf_counter floats convert losslessly to ns
            # at process-uptime magnitudes, so the stamps below reuse
            # the timestamps the stage timings already take
            cview.calibrate()
        t0 = time.perf_counter()
        # parse_spans sniffs the wire format: JSON v2 and proto3
        # ListOfSpans both land here, so the fan-out is format-agnostic
        parsed = (
            native.parse_spans(payload, nvocab=nvocab)
            if nvocab is not None
            else None
        )
        if parsed is None:
            # the strict-codec fallback needs Span objects: punt back to
            # the dispatcher, which still holds the payload bytes
            state["completed"] = True
            result_q.put((_KIND_FALLBACK, widx, pid, prod.next_wseq()))
            return
        nvocab.sync()
        n = parsed.n
        dropped = 0
        if boundary is not None and n:
            keep = native.sampler_keep(parsed, n, boundary)
            dropped = int(n - keep.sum())
            if dropped:
                idx = np.nonzero(keep)[0]
                for field in PARSED_FIELDS:
                    col = getattr(parsed, field, None)
                    if col is not None:
                        setattr(parsed, field, col[:n][idx])
                parsed.n = n = len(idx)
        parse_s = time.perf_counter() - t0
        if traced:
            cview.stamp(
                cslot, SEG_PARSE, int(t0 * 1e9),
                int((t0 + parse_s) * 1e9),
            )
        if n == 0:
            state["completed"] = True
            result_q.put(
                (_KIND_BATCH, widx, pid, prod.next_wseq(), None, 0, 0, 0,
                 dropped, [], [], [], [], (0, 0), None, parse_s, 0.0, 0.0)
            )
            return
        for lo in range(0, n, max_batch):
            hi = min(lo + max_batch, n)
            if lo == 0 and hi == n:
                sub = parsed
            else:
                sub = native.ParsedColumns()
                sub.data = parsed.data
                for f in PARSED_FIELDS:
                    col = getattr(parsed, f, None)
                    setattr(sub, f, None if col is None else col[lo:hi])
                sub.n = hi - lo
            t1 = time.perf_counter()
            cols = pack_parsed(sub, vocab, pad)
            t2 = time.perf_counter()
            fused = route_fused(cols, n_shards)
            route_s = time.perf_counter() - t2
            pack_s = t2 - t1
            if traced:
                cview.stamp(cslot, SEG_PACK, int(t1 * 1e9), int(t2 * 1e9))
                cview.stamp(
                    cslot, SEG_ROUTE, int(t2 * 1e9),
                    int((t2 + route_s) * 1e9),
                )
            arch = _extract_archive_slices(sub, every)
            rec = parsed_record(sub) if disk else None
            # vocab journal since the last report (id order)
            svc_new = vocab.services._names[sent_svc:]
            name_new = vocab.span_names._names[sent_name:]
            pairs_new = vocab._key_list[sent_pair:]
            sent_svc += len(svc_new)
            sent_name += len(name_new)
            sent_pair += len(pairs_new)
            n_spans = int(cols.valid.sum())
            n_dur = int((cols.valid & cols.has_dur).sum())
            n_err = int((cols.valid & cols.err).sum())
            live_ts = cols.ts_min[cols.valid]
            ts_range = (
                (int(live_ts.min()), int(live_ts.max()))
                if live_ts.size
                else (0, 0)
            )
            # -1 marks a continuation chunk: the dispatcher completes a
            # payload (applies its buffered chunks, decrements inflight)
            # on the LAST chunk only, so drain() can never return while
            # later chunks are still queued or being packed (ADVICE r3).
            # The sampled-drop count rides the completion chunk.
            is_last = hi == n
            if is_last:
                state["completed"] = True
            aux = pack_aux(svc_new, name_new, pairs_new, arch, rec)
            if fused.size <= prod.img_cap_u32 and len(aux) <= prod.aux_cap:
                ta = time.perf_counter()
                prod.claim()
                tb = time.perf_counter()
                if traced:
                    cview.stamp(
                        cslot, SEG_RING_WAIT, int(ta * 1e9), int(tb * 1e9)
                    )
                prod.image(fused.size)[:] = fused.reshape(-1)
                # the wseq is allocated at the last infallible instant
                # before emission on BOTH channels, so a worker that
                # survives an exception can never leave a sequence gap
                # that would stall the dispatcher's in-order pump
                prod.publish(
                    pidx=pid, wseq=prod.next_wseq(),
                    per=int(fused.shape[-1]),
                    n_spans=n_spans, n_dur=n_dur, n_err=n_err,
                    dropped=dropped if is_last else -1,
                    cslot=cslot if traced else -1,
                    ts_min=ts_range[0], ts_max=ts_range[1],
                    parse_ns=int(parse_s * 1e9),
                    pack_ns=int(pack_s * 1e9),
                    route_ns=int(route_s * 1e9),
                    tenant=tidx,
                    aux=aux,
                )
                # a ring publish carries no wakeup of its own: nudge
                # the dispatcher so a backed-off idle poll (up to
                # 50 ms) doesn't sit out its full interval while a
                # ready slot waits
                result_q.put((_KIND_NUDGE,))
            else:
                # sidecar outgrew the bounded slot (huge disk-archive
                # record): ship the whole chunk through the queue — the
                # wseq keeps it ordered against the ring chunks
                result_q.put(
                    (_KIND_BATCH, widx, pid, prod.next_wseq(), fused,
                     n_spans, n_dur, n_err,
                     dropped if is_last else -1,
                     svc_new, name_new, pairs_new, arch, ts_range, rec,
                     parse_s, pack_s, route_s)
                )
            parse_s = 0.0  # only bill the parse once per payload

    try:
        while True:
            item = work_q.get()
            if item is None:
                break
            pid, payload, cslot, tidx = item
            state: dict = {"completed": False}
            try:
                handle(pid, payload, state, cslot, tidx)
            except Exception:  # pragma: no cover - keep the pool alive
                logging.getLogger(__name__).exception(
                    "mp-ingest worker %d failed on a payload", widx
                )
                if not state["completed"]:
                    # the dispatcher buffers chunk application until the
                    # completion marker, so any chunks this payload DID
                    # ship were never applied: a whole-payload fallback
                    # retry cannot double-ingest, and nothing is lost
                    result_q.put(
                        (_KIND_FALLBACK, widx, pid, prod.next_wseq())
                    )
    finally:
        result_q.put((_KIND_EOF, widx))
        if cview is not None:
            cview.close()
        prod.close()


class _IdMaps:
    """Worker-local -> global id tables, grown as journals arrive."""

    def __init__(self) -> None:
        self.svc = np.zeros(1, np.uint32)  # local id 0 -> global 0
        self.name = np.zeros(1, np.uint32)
        self.key = np.zeros(1, np.uint32)

    @staticmethod
    def _append(arr: np.ndarray, values: List[int]) -> np.ndarray:
        return np.concatenate([arr, np.asarray(values, np.uint32)]) if values else arr


class MultiProcessIngester:
    """Owns the worker pool + the span ring + the dispatcher thread.

    ``submit(payload)`` enqueues raw JSON v2 / proto3 bytes onto one
    live worker and returns once the payload is accepted.
    ``submit(payload, block=False)`` — the server boundary's mode —
    raises :class:`IngestBackpressure` instead of blocking when every
    live worker is saturated (ring stripe or delivery queue full).
    ``drain()`` blocks until everything submitted has reached the
    device. ``coalesce_max`` bounds how many ready chunks one flush may
    merge into a single device step + WAL record; the default of 1
    keeps per-chunk dispatch — and the WAL byte stream — identical to
    the pre-ring path. Parity with ``TpuStorage.ingest_json_fast`` —
    same sketches, same sampling verdicts, same WAL contents — is
    asserted in tests/test_mp_ingest.py and tests/test_fanout_parity.py.
    """

    def __init__(
        self,
        store,
        workers: int = 2,
        slots_per_worker: int = 2,
        sampler=None,
        queue_depth: Optional[int] = None,
        metrics=None,
        critpath_slots: int = 0,
        critpath_reclaim_s: float = 60.0,
        ring_slots: int = 0,
        coalesce_max: int = 1,
        ring_aux_bytes: int = 1 << 20,
    ) -> None:
        from zipkin_tpu import native
        from zipkin_tpu.tpu.columnar import WIRE_ROWS

        if not native.available():
            raise RuntimeError("native codec unavailable; MP tier needs it")
        self.store = store
        self.workers = workers
        self.queue_depth = queue_depth or 2  # PER-WORKER payload bound
        self.coalesce_max = max(1, int(coalesce_max))
        self._sampler = sampler
        agg = store.agg
        self._n_shards = agg.n_shards
        self._wire_rows = WIRE_ROWS
        # worst case: every span of a max_batch chunk routes to one
        # shard, and route_fused rounds the per-shard lane count up to
        # its 256 pad multiple — ring slots must cover the ROUNDED bound
        # or a near-full chunk would spill past its image region
        per_cap = ((store.max_batch + 255) // 256) * 256
        img_cap_u32 = agg.n_shards * WIRE_ROWS * per_cap
        stripe = int(ring_slots) if ring_slots else max(
            4, 2 * slots_per_worker
        )
        self._ring = ring_mod.SpanRing(
            workers, stripe, img_cap_u32, aux_cap=int(ring_aux_bytes)
        )
        ctx = mp.get_context("spawn")
        # one bounded delivery queue per worker: payload handoff + the
        # second backpressure surface (a frozen worker's stripe stays
        # empty, so ring occupancy alone would never push back on it)
        self._work_qs = [
            ctx.Queue(maxsize=self.queue_depth) for _ in range(workers)
        ]
        self._result_q = ctx.Queue()
        has_disk = getattr(store, "_disk", None) is not None
        params = dict(
            max_services=store.vocab.services.capacity,
            max_keys=store.vocab.max_keys,
            n_shards=agg.n_shards,
            max_batch=store.max_batch,
            pad=store._pad,
            # workers build per-chunk raw-archive records (payload +
            # index columns, worker-local ids) that the dispatcher
            # remaps and appends — the MP tier and the complete trace
            # store are no longer mutually exclusive (VERDICT r4 order
            # 2). The RAM 1/N sample then only matters for
            # autocompleteTags, exactly like the sync fast path.
            archive_disk=has_disk,
            archive_every=(
                store._fast_archive_every
                if (not has_disk or store.autocomplete_keys)
                else 0
            ),
            sample_boundary=(
                sampler._boundary
                if sampler is not None and sampler.rate < 1.0
                else None
            ),
        )
        # critical-path interval ledger (obs/critpath.py): created before
        # the pool spawns so workers attach by name. The stitcher is
        # exposed as .critpath; the server registers it on the windows
        # ticker and the statusz/bench report reads its waterfall.
        self._cp_ledger = None
        self.critpath = None
        self._cslots: Dict[int, int] = {}
        if critpath_slots > 0:
            self._cp_ledger = _critpath.CritPathLedger(
                workers, critpath_slots
            )
            self.critpath = _critpath.CritPathStitcher(
                self._cp_ledger,
                queue_capacity=workers * self.queue_depth,
                recorder=obs.RECORDER,
                reclaim_age_s=critpath_reclaim_s,
            )
            params["critpath"] = self._cp_ledger.params()
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(
                    w, self._work_qs[w], self._result_q,
                    self._ring.params(), params,
                ),
                daemon=True,
            )
            for w in range(workers)
        ]
        for p in self._procs:
            p.start()
        self.metrics = metrics  # CollectorMetrics-shaped, optional
        # accuracy-observatory tap (obs/shadow.py): when attached, every
        # applied chunk's fused image is offered (ring-slot views are
        # copied first — the tap may retain its argument past the slot's
        # reuse)
        self.shadow = None
        # tenant attribution (ISSUE 18): a bounded intern table maps the
        # boundary's tenant string to a small idx that rides the queue
        # item, the ring slot header, and the critpath ledger. Overflow
        # collapses onto idx 0 (the default tenant) — a hostile stream
        # of unique tenant ids cannot grow this table unboundedly.
        # tenant_sink (optional; called on the DISPATCHER thread at ack
        # time, must be thread-safe) receives (tenant, n_spans) so the
        # admission table can account retained-spans/sec budgets.
        self._tenant_names: List[str] = ["default"]
        self._tenant_ids: Dict[str, int] = {"default": 0}
        self._tenant_max = 256
        self._tenant_of: Dict[int, int] = {}
        self._tenant_acked: Dict[str, Dict[str, int]] = {}
        self.tenant_sink = None
        self.counters = {
            "accepted": 0, "sampleDropped": 0, "fallbacks": 0, "rejected": 0,
            "coalescedBatches": 0, "coalescedChunks": 0,
            "ringDiscarded": 0, "ringTorn": 0,
        }
        # per-worker attribution (chunks carry widx): a slow worker is
        # distinguishable from a slow pool. Mutated only on the
        # dispatcher thread; read lock-free by stats().
        self._wstats = [
            {"chunks": 0, "spans": 0, "payloads": 0, "parseUs": 0,
             "packUs": 0, "routeUs": 0, "fallbacks": 0}
            for _ in range(workers)
        ]
        # live per-worker occupancy (submitted minus finished) and its
        # high-water mark — the between-ticks saturation signal the
        # cumulative tallies above cannot show. Mutated under _cv.
        self._qdepth = [0] * workers
        self._qhigh = [0] * workers
        self._ring_high = 0
        self._inflight = 0
        self._cv = threading.Condition()
        self._closed = False
        self._dispatch_error: Optional[BaseException] = None
        # payload retention until APPLIED (zero-loss worker death):
        # _pending maps payload id -> raw bytes, _assigned -> the worker
        # that owns it, _buffered -> its not-yet-applied chunk results.
        # _pending/_assigned are mutated by submit() (under _cv) and by
        # the dispatcher thread; _buffered only by the dispatcher.
        self._next_pid = 0
        self._rr = 0
        self._pending: Dict[int, bytes] = {}
        self._assigned: Dict[int, int] = {}
        self._buffered: Dict[int, list] = {}
        self._dead: Set[int] = set()
        self._maps: List[Optional[_IdMaps]] = [
            _IdMaps() for _ in range(workers)
        ]
        # cross-channel in-order pump state (dispatcher thread only):
        # the next wseq to apply per worker, plus queue messages that
        # arrived ahead of their turn
        self._expected = [0] * workers
        self._holdback: List[Dict[int, tuple]] = [
            {} for _ in range(workers)
        ]
        self._pending_eof: Set[int] = set()
        self._reap_later: List[int] = []
        # reap reentrancy guard: _reap_dead_workers drains result_q and
        # pumps, which can discover ANOTHER premature EOF — a recursive
        # reap would abort the outer one before its salvage ran
        # (ADVICE r4). Extra dead workers found mid-reap are collected
        # here and folded into the current reap instead.
        self._reaping = False
        self._reap_extra: List[int] = []
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="mp-ingest-dispatch", daemon=True
        )
        self._dispatcher.start()

    # -- producer side ---------------------------------------------------

    def _tenant_idx(self, tenant: Optional[str]) -> int:
        """Intern a boundary tenant id into the bounded idx table;
        unknown tenants past the cap collapse onto the default idx 0."""
        if not tenant or tenant == "default":
            return 0
        idx = self._tenant_ids.get(tenant)
        if idx is not None:
            return idx
        with self._cv:
            idx = self._tenant_ids.get(tenant)
            if idx is not None:
                return idx
            if len(self._tenant_names) >= self._tenant_max:
                return 0
            idx = len(self._tenant_names)
            self._tenant_names.append(tenant)
            self._tenant_ids[tenant] = idx
            return idx

    def submit(self, payload: bytes, *, block: bool = True,
               tenant: Optional[str] = None) -> None:
        """Enqueue a payload onto one live unsaturated worker.

        Registration happens BEFORE the queue put (under _cv, the same
        lock the reaper takes to mark workers dead), so a worker-death
        reap is linearized against submission: either the reap sees the
        registration and refeeds the payload, or submit() sees the
        worker marked dead and picks another. A worker whose ring
        stripe is full is skipped exactly like one whose queue is full
        — ring occupancy is the tier's backpressure basis. ``tenant``
        (the boundary-extracted id) rides the queue item, the ring slot
        header, and the critpath ledger so ack-time accounting stays
        tenant-attributed end to end.
        """
        tidx = self._tenant_idx(tenant)
        while True:
            if self._closed:
                raise RuntimeError("ingester closed")
            if self._dispatch_error is not None:
                raise RuntimeError(
                    "dispatcher died"
                ) from self._dispatch_error
            with self._cv:
                live = [
                    w for w in range(self.workers) if w not in self._dead
                ]
                if not live:
                    raise RuntimeError(
                        "mp-ingest worker pool exhausted (every worker "
                        "died); restart the ingester"
                    )
                start = self._rr % len(live)
                self._rr += 1
                pid = self._next_pid
                self._next_pid += 1
                self._pending[pid] = payload
                if tidx:
                    self._tenant_of[pid] = tidx
                self._inflight += 1
            wire_ns = (
                _critpath.WIRE_T0_NS.get()
                if self._cp_ledger is not None
                else 0
            )
            for relax in (False, True):
                for w in live[start:] + live[:start]:
                    with self._cv:
                        if w in self._dead:
                            continue
                        self._assigned[pid] = w
                    if not relax and self._ring.stripe_full(w):
                        # the dispatcher is behind on this stripe:
                        # first round prefers a worker with drain
                        # headroom. Ring congestion alone must NOT
                        # reject — the worker's blocking claim()
                        # propagates the ring bound back through its
                        # delivery queue — so a second round relaxes
                        # the check and only full queues remain
                        with self._cv:
                            if pid not in self._pending:
                                return  # a racing reap already refed it
                            if self._assigned.get(pid) == w:
                                self._assigned.pop(pid)
                        continue
                    cslot = -1
                    if wire_ns:
                        t_en0 = time.perf_counter_ns()
                        cslot = self._cp_ledger.alloc(
                            pid, w, wire_ns, tenant=tidx
                        )
                        if cslot >= 0:
                            # stamp + register BEFORE the queue put: the
                            # dispatcher only writes this slot after the
                            # worker's chunk arrives, so main-side
                            # region writers stay causally serialized
                            self._cp_ledger.stamp(
                                cslot, _critpath.SEG_ENQUEUE, t_en0,
                                time.perf_counter_ns(), pid,
                            )
                            with self._cv:
                                self._cslots[pid] = cslot
                    try:
                        self._work_qs[w].put_nowait(
                            (pid, payload, cslot, tidx)
                        )
                        with self._cv:
                            self._qdepth[w] += 1
                            if self._qdepth[w] > self._qhigh[w]:
                                self._qhigh[w] = self._qdepth[w]
                        return
                    except queue.Full:
                        if cslot >= 0:
                            with self._cv:
                                self._cslots.pop(pid, None)
                            self._cp_ledger.abandon(cslot)
                        with self._cv:
                            if pid not in self._pending:
                                return  # a racing reap already refed it
                            if self._assigned.get(pid) == w:
                                self._assigned.pop(pid)
            # every live worker is saturated: roll the registration back
            with self._cv:
                if pid not in self._pending:
                    return  # a racing reap consumed it
                self._pending.pop(pid)
                self._assigned.pop(pid, None)
                self._tenant_of.pop(pid, None)
                self._inflight -= 1
                if self._inflight == 0:
                    self._cv.notify_all()
            if not block:
                self.counters["rejected"] += 1
                raise IngestBackpressure(
                    f"ingest fan-out saturated: every live worker's "
                    f"delivery queue is full behind its ring stripe "
                    f"({len(live)} workers x queue depth "
                    f"{self.queue_depth}, {self._ring.stripe_slots} "
                    f"ring slots each); retry after backoff"
                )
            time.sleep(0.002)

    def drain(self) -> None:
        """Block until every submitted payload has reached the device."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._inflight == 0 or self._dispatch_error is not None
            )
        if self._dispatch_error is not None:
            raise RuntimeError("dispatcher died") from self._dispatch_error
        # zt-lint: disable=ZT06 — drain's contract IS the blocking sync:
        # "until every payload has reached the device" means retire the
        # device queue, not just the dispatch threads
        self.store.agg.block_until_ready()

    def stats(self) -> dict:
        """Fan-out tier gauges, merged into TpuStorage.ingest_counters()
        so /metrics and /statusz show the tier."""
        with self._cv:
            inflight = self._inflight
            dead = len(self._dead)
            qdepth = list(self._qdepth)
            qhigh = list(self._qhigh)
        out = {
            "mpWorkers": self.workers,
            "mpWorkersAlive": self.workers - dead,
            "mpQueueDepth": self.queue_depth,
            "mpInflight": inflight,
            "mpAccepted": self.counters["accepted"],
            "mpSampleDropped": self.counters["sampleDropped"],
            "mpFallbacks": self.counters["fallbacks"],
            "mpRejected": self.counters["rejected"],
            "mpRingSlots": self._ring.capacity,
            "mpRingOccupancy": self._ring.occupancy(),
            "mpRingHighWater": self._ring_high,
            "mpCoalesceMax": self.coalesce_max,
            "mpCoalescedBatches": self.counters["coalescedBatches"],
            "mpCoalescedChunks": self.counters["coalescedChunks"],
            "mpRingDiscarded": self.counters["ringDiscarded"],
            "mpRingTorn": self.counters["ringTorn"],
            # nested per-worker table — scalar-only consumers
            # (/prometheus gauge emission) skip non-scalar values
            "mpWorkerTable": [
                {"widx": w, "alive": w not in self._dead,
                 "queueDepth": qdepth[w], "queueHighWater": qhigh[w],
                 "ringDepth": self._ring.stripe_depth(w),
                 **dict(ws)}
                for w, ws in enumerate(self._wstats)
            ],
            # per-tenant acked attribution (ISSUE 18) — nested like the
            # worker table; bounded by the tenant intern cap
            "mpTenantTable": {
                name: dict(row)
                for name, row in self._tenant_acked.items()
            },
        }
        if self.critpath is not None:
            out.update(self.critpath.counters())
        return out

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for w, p in enumerate(self._procs):
            if w in self._dead:
                continue  # no consumer; nothing to shut down
            # per-worker bounded queue: a live worker keeps consuming,
            # so a timed put retried until it lands cannot hang; a
            # worker that died mid-shutdown just stops needing one
            while True:
                try:
                    self._work_qs[w].put(None, timeout=0.5)
                    break
                except queue.Full:
                    if not p.is_alive():
                        break
        for p in self._procs:
            p.join(timeout=30)
            if p.is_alive():  # pragma: no cover - hang safety
                p.terminate()
        self._dispatcher.join(timeout=30)
        for q in self._work_qs:
            # a dead worker's queue may still hold (already-salvaged)
            # payloads; don't let its feeder thread block interpreter
            # exit flushing them to a pipe nobody reads
            q.close()
            q.cancel_join_thread()
        if self._dispatch_error is not None:
            # the stored exception's traceback pins frames whose locals
            # can include ndarray VIEWS into ring slots — shm close()
            # would refuse ("exported pointers exist"). The dispatcher
            # thread is joined, so the frames are safe to clear;
            # drain()'s re-raise keeps the message.
            import traceback

            tb = self._dispatch_error.__traceback__
            if tb is not None:
                traceback.clear_frames(tb)
        self._buffered.clear()
        self._ring.close()
        if self._cp_ledger is not None:
            self._cp_ledger.close()

    # -- dispatcher ------------------------------------------------------

    def _dispatch_loop(self) -> None:
        try:
            self._run_dispatch()
        except BaseException as e:
            logger.exception("mp-ingest dispatcher failed")
            self._dispatch_error = e
            with self._cv:
                self._cv.notify_all()
            self._sink_until_closed()

    def _sink_until_closed(self) -> None:
        """After a dispatcher failure, keep draining result_q and
        freeing ring slots so SURVIVING workers never wedge in
        ``claim()`` with the only consumer (the normal dispatch loop)
        gone — otherwise close() would burn its full join timeout per
        live worker and terminate() it mid-payload. Results are
        discarded: the error is already surfaced to submit()/drain(),
        so callers know batches after the failure point are lost."""
        while True:
            for w in range(self.workers):
                while self._ring.stripe_depth(w) > 0:
                    self._ring.free_next(w)
            try:
                self._result_q.get(timeout=0.25)
            except queue.Empty:
                if self._closed and not any(p.is_alive() for p in self._procs):
                    return

    def _run_dispatch(self) -> None:
        eof_set: set = set()
        last_liveness = time.monotonic()
        idle_wait = 0.0005
        while len(eof_set) < self.workers:
            if self._pass(eof_set):
                idle_wait = 0.0005
            else:
                # nothing ready anywhere: block on the control queue —
                # ring publishes wake it via a nudge message, and the
                # timeout doubles as a poll backstop, backing off while
                # idle (a nudge can race the pass that already consumed
                # its slot, so the poll still matters)
                try:
                    msg = self._result_q.get(timeout=idle_wait)
                except queue.Empty:
                    if self._closed and not any(
                        p.is_alive() for p in self._procs
                    ):
                        self._pass(eof_set)  # final sweep
                        break
                    idle_wait = min(idle_wait * 2, 0.05)
                else:
                    self._route_msg(msg, eof_set)
                    idle_wait = 0.0005
            # liveness must ALSO run under sustained traffic: a busy
            # surviving worker keeps the ring non-empty, so the idle
            # branch alone could leave a dead worker's acked payloads
            # pinning _inflight for as long as load lasts
            if (
                not self._closed
                and time.monotonic() - last_liveness > 2.0
            ):
                self._check_liveness(eof_set)
                last_liveness = time.monotonic()

    def _pass(self, eof_set: set) -> bool:  # zt-dispatch-critical: one drain pass — consume ready slots, flush completed payloads coalesced, free slots
        """One dispatcher pass: drain the control queue, pump every live
        stripe's contiguous run of ready slots in wseq order, flush the
        payloads that completed (coalesced), materialize any view still
        buffered for an incomplete payload, then free the consumed slots
        — so no slot is ever held across passes and a multi-chunk
        payload cannot starve its own worker of ring capacity."""
        activity = False
        # zt-lint: disable=ZT09 — per queued control MESSAGE (chunk- or
        # payload-granular), never per span
        while True:
            try:
                msg = self._result_q.get_nowait()
            except queue.Empty:
                break
            self._route_msg(msg, eof_set)
            activity = True
        ready: List[tuple] = []
        consumed: Dict[int, int] = {}
        self._pump(ready, consumed)
        occ = self._ring.occupancy()  # zt-lint: disable=ZT09 — O(n_workers) stripe-depth word reads
        if occ > self._ring_high:
            self._ring_high = occ
        if ready:
            self._flush_ready(ready)
        if consumed:
            self._materialize_views()  # zt-lint: disable=ZT09 — per straddling-payload CHUNK copy, bounded by stripe depth × workers, not span count
            # zt-lint: disable=ZT09 — per worker STRIPE with consumed slots
            for w, cnt in consumed.items():
                for _ in range(cnt):  # zt-lint: disable=ZT09 — per consumed SLOT (chunk-sized), a word store + counter bump each
                    self._ring.free_next(w)
            activity = True
        if self._reap_later and not self._reaping:
            # zt-lint: disable=ZT09 — per deferred-reap WORKER
            dead = [w for w in self._reap_later if w not in eof_set]
            self._reap_later = []
            if dead:
                self._reap_dead_workers(dead, eof_set)  # zt-lint: disable=ZT09 — rare worker-death recovery path, trips per dead worker / inflight payload, not steady-state dispatch
                activity = True
        # zt-lint: disable=ZT09 — per EOF-pending WORKER, two integer reads
        for w in list(self._pending_eof):
            if (
                self._ring.stripe_depth(w) == 0
                and not self._holdback[w]
            ):
                self._pending_eof.discard(w)
                eof_set.add(w)
                activity = True
        return activity or bool(ready)

    def _route_msg(self, msg, eof_set: set) -> None:
        """Sort one control-queue message: EOFs resolve now (clean) or
        mark the worker for reaping (premature); chunk/fallback messages
        park in the per-worker holdback until their wseq turn."""
        kind = msg[0]
        if kind == _KIND_NUDGE:
            return  # wakeup only — the pump reads the ring directly
        if kind == _KIND_EOF:
            widx = msg[1]
            if self._closed or widx in self._dead:
                # clean shutdown: finalized once the stripe drains
                self._pending_eof.add(widx)
                if widx in self._dead:
                    self._pending_eof.discard(widx)
                    eof_set.add(widx)
            elif self._reaping:
                self._reap_extra.append(widx)
            else:
                # workers only EOF after close()'s None sentinel; an EOF
                # before close() means the worker loop was torn down by
                # a BaseException with its inflight payloads unaccounted
                # — treat it exactly like an unclean death and refeed
                # (deferred to the pass tail so payloads already
                # completed in this pass flush before the reap scan)
                self._reap_later.append(widx)
            return
        widx, wseq = msg[1], msg[3]
        if widx in self._dead:
            return
        self._holdback[widx][wseq] = msg

    def _pump(self, ready: List[tuple], consumed: Dict[int, int]) -> None:  # zt-dispatch-critical: in-order merge of ring slots + queue stragglers per worker
        """Apply every worker's available chunks strictly in wseq order,
        merging the ring stripe with held-back queue messages. Stops per
        worker at the first missing sequence (still in flight on the
        other channel)."""
        # zt-lint: disable=ZT09 — per WORKER stripe
        for w in range(self.workers):
            if w in self._dead:
                continue
            budget = self._ring.stripe_slots + len(self._holdback[w]) + 1
            while budget > 0:  # zt-lint: disable=ZT09 — bounded by stripe depth + holdback, each iteration applies one chunk
                budget -= 1
                exp = self._expected[w]
                hb = self._holdback[w].pop(exp, None)
                if hb is not None:
                    self._apply_queue_msg(hb, ready)
                    self._expected[w] = exp + 1
                    continue
                peeked = self._ring.peek(w, consumed.get(w, 0))
                if peeked is None:
                    break
                hdr, seq = peeked
                if int(hdr[ring_mod._S_WSEQ]) != exp:
                    break  # the missing wseq is in flight on the queue
                self._consume_ring_chunk(w, hdr, seq, ready)
                consumed[w] = consumed.get(w, 0) + 1
                self._expected[w] = exp + 1

    def _consume_ring_chunk(
        self, w: int, hdr: np.ndarray, seq: int, ready: List[tuple]
    ) -> None:  # zt-dispatch-critical: zero-copy slot consume — header decode + vocab replay, no image copy
        t0 = time.perf_counter()
        pid = int(hdr[ring_mod._S_PIDX])
        if pid not in self._pending:
            # late chunk of a payload a reap already refed: discard (the
            # slot is still counted consumed and freed by the pass)
            self.counters["ringDiscarded"] += 1
            return
        # tenant idx rides the slot header cross-process; the submit
        # side already recorded it, but the ring word is authoritative
        # for chunks (it survives even when attribution maps are cold)
        tidx = int(hdr[ring_mod._S_TENANT])
        if tidx and pid not in self._tenant_of:
            # zt-lint: disable=ZT04 — single-writer-per-pid: submit()
            # records the mapping under _cv BEFORE the worker can publish
            # a chunk; this dispatcher-thread write only fills pids whose
            # submit-side record was skipped (tidx==0 fast path), and no
            # other thread touches that pid's key
            self._tenant_of[pid] = tidx
        per = int(hdr[ring_mod._S_PER])
        fused = self._ring.image(
            w, seq, self._n_shards * self._wire_rows * per
        ).reshape(self._n_shards, self._wire_rows, per)
        aux_len = int(hdr[ring_mod._S_AUX_LEN])
        svc_new, name_new, pairs_new, arch, rec = ring_mod.unpack_aux(
            self._ring.aux(w, seq, aux_len)
        )
        self._apply_chunk(
            w, pid, fused,
            int(hdr[ring_mod._S_NSPANS]), int(hdr[ring_mod._S_NDUR]),
            int(hdr[ring_mod._S_NERR]), int(hdr[ring_mod._S_DROPPED]),
            svc_new, name_new, pairs_new, arch,
            (int(hdr[ring_mod._S_TS_MIN]), int(hdr[ring_mod._S_TS_MAX])),
            rec,
            int(hdr[ring_mod._S_PARSE_NS]) / 1e9,
            int(hdr[ring_mod._S_PACK_NS]) / 1e9,
            int(hdr[ring_mod._S_ROUTE_NS]) / 1e9,
            True, time.perf_counter() - t0, ready,
        )

    def _apply_queue_msg(self, msg, ready: List[tuple]) -> None:
        kind = msg[0]
        if kind == _KIND_FALLBACK:
            _, widx, pid, _wseq = msg
            payload = self._pending.get(pid)
            if payload is None:
                return  # a reap already refed it
            self._buffered.pop(pid, None)
            self._drop_cslot(pid)  # slow-path retry: timeline abandoned
            self._fallback(payload)
            self.counters["fallbacks"] += 1
            if 0 <= widx < len(self._wstats):
                self._wstats[widx]["fallbacks"] += 1
            self._finish(pid)
            return
        (
            _, widx, pid, _wseq, fused, n_spans, n_dur, n_err, dropped,
            svc_new, name_new, pairs_new, arch, ts_range, rec,
            parse_s, pack_s, route_s,
        ) = msg
        t0 = time.perf_counter()
        if pid not in self._pending:
            return
        self._apply_chunk(
            widx, pid, fused, n_spans, n_dur, n_err, dropped,
            svc_new, name_new, pairs_new, arch, ts_range, rec,
            parse_s, pack_s, route_s,
            False, time.perf_counter() - t0, ready,
        )

    def _apply_chunk(
        self, widx, pid, fused, n_spans, n_dur, n_err, dropped,
        svc_new, name_new, pairs_new, arch, ts_range, rec,
        parse_s, pack_s, route_s, is_view, consume_s, ready,
    ) -> None:  # zt-dispatch-critical: per-chunk apply — vocab journal replay + buffer append on the single dispatch thread
        store = self.store
        vocab = store.vocab
        m = self._maps[widx]
        cs = self._cslots.get(pid, -1) if self._cp_ledger is not None else -1
        if svc_new or name_new or pairs_new:
            tv0 = time.perf_counter()
            with store._intern_lock:
                # zt-lint: disable=ZT09 — journal replay is per NEWLY
                # INTERNED STRING (bounded by vocab capacity, amortized
                # zero per span), not per span
                m.svc = _IdMaps._append(
                    m.svc, [vocab.services.intern(s) for s in svc_new]
                )
                # zt-lint: disable=ZT09 — per new string, as above
                m.name = _IdMaps._append(
                    m.name, [vocab.span_names.intern(s) for s in name_new]
                )
                # zt-lint: disable=ZT09 — per new (svc, name) pair
                m.key = _IdMaps._append(
                    m.key,
                    [
                        vocab.key_id(int(m.svc[sl]), int(m.name[nl]))
                        for sl, nl in pairs_new
                    ],
                )
            tv1 = time.perf_counter()
            obs.record("mp_vocab_replay", tv1 - tv0)
            if cs >= 0:
                self._cp_ledger.stamp(
                    cs, _critpath.SEG_VOCAB_REPLAY,
                    int(tv0 * 1e9), int(tv1 * 1e9), pid,
                )
        # worker-measured stage wall time: the workers can't touch the
        # in-process flight recorder, so their parse/pack/route timings
        # ride the chunk and are recorded here. record_relayed
        # (histogram-only): the time was spent in a worker process, so a
        # budget crossing must not emit a self-span B3-linked to
        # whatever request context this dispatcher thread holds.
        if parse_s > 0.0:
            obs.record_relayed("parse", parse_s)
        if pack_s > 0.0:
            obs.record_relayed("pack", pack_s)
        if route_s > 0.0:
            obs.record_relayed("route", route_s)
        ws = self._wstats[widx]
        ws["chunks"] += 1
        ws["spans"] += n_spans
        ws["parseUs"] += int(parse_s * 1e6 + 0.5)
        ws["packUs"] += int(pack_s * 1e6 + 0.5)
        ws["routeUs"] += int(route_s * 1e6 + 0.5)
        if dropped >= 0:
            ws["payloads"] += 1
        if fused is not None:
            if rec is not None:
                # remap the record's svc/rsvc/name/key lanes local ->
                # global NOW (the journal above covers every id this
                # chunk references; the maps may have grown by apply
                # time); append is deferred to the completion flush
                rec = list(rec)
                rec[7] = m.svc[rec[7]]
                rec[8] = m.svc[rec[8]]
                rec[9] = m.name[rec[9]]
                rec[10] = m.key[rec[10]]
                rec = tuple(rec)
            self._buffered.setdefault(pid, []).append(
                [fused, n_spans, n_dur, n_err, ts_range, arch, rec,
                 consume_s, is_view, widx]
            )
        # dropped == -1 marks a continuation chunk; the payload is
        # applied atomically once its LAST chunk has been consumed
        if dropped >= 0:
            ready.append((pid, dropped))

    def _materialize_views(self) -> None:
        """Chunks still buffered for an INCOMPLETE payload at pass end
        get copied out of their ring slots (the pre-ring per-chunk copy,
        now paid only by payloads that straddle a pass) so every
        consumed slot can be freed — a payload can never pin its
        worker's stripe while waiting for its own later chunks."""
        for pid, entries in self._buffered.items():
            for e in entries:
                if not e[8]:
                    continue
                t0 = time.perf_counter()
                e[0] = np.array(e[0])
                e[8] = False
                tc1 = time.perf_counter()
                obs.record("mp_shm_copy", tc1 - t0)
                cs = (
                    self._cslots.get(pid, -1)
                    if self._cp_ledger is not None else -1
                )
                if cs >= 0:
                    self._cp_ledger.stamp(
                        cs, _critpath.SEG_SHM_COPY,
                        int(t0 * 1e9), int(tc1 * 1e9), pid,
                    )

    # -- coalesced flush --------------------------------------------------

    def _flush_ready(self, ready: List[tuple]) -> None:  # zt-dispatch-critical: applies completed payloads to the device + durability path, coalesced
        """Flush the payloads completed this pass: their buffered chunks
        are packed into groups of up to ``coalesce_max`` chunks (bounded
        by the aggregator's lane cap) and each group takes ONE
        ``ingest_fused_multi`` — whose dispatch side carries the WAL
        append and sampling verdicts, preserving ack-after-durability
        exactly like the serial path. Until this runs, a payload has
        mutated nothing, which is what makes worker death recoverable.
        A payload's chunks may split across groups (the same
        at-least-once boundary the per-chunk path always had); its ack
        fires only after the group holding its last chunk — and, when
        several groups share one vectored WAL commit, after that commit.
        """
        store = self.store
        plans: Dict[int, dict] = {}
        flat: List[tuple] = []
        # zt-lint: disable=ZT09 — per completed PAYLOAD
        for pid, dropped in ready:
            entries = self._buffered.pop(pid, [])
            # zt-lint: disable=ZT09 — per buffered CHUNK of one payload
            plans[pid] = {
                "dropped": dropped,
                "left": len(entries),
                "spans": sum(e[1] for e in entries),
                "consume_s": sum(e[7] for e in entries),
            }
            # zt-lint: disable=ZT09 — per buffered CHUNK, a list append
            for e in entries:
                flat.append((e, pid))
        cap = store.agg.lane_cap
        groups: List[List[tuple]] = []
        cur: List[tuple] = []
        lanes = 0
        for e, pid in flat:  # zt-lint: disable=ZT09 — per chunk: greedy group packing, integer bookkeeping only
            per = int(e[0].shape[-1])
            if cur and (
                len(cur) >= self.coalesce_max or lanes + per > cap
            ):
                groups.append(cur)
                cur, lanes = [], 0
            cur.append((e, pid))
            lanes += per
        if cur:
            groups.append(cur)
        wal = getattr(store, "wal", None)
        if wal is not None and len(groups) > 1:
            # one vectored WAL commit for the whole pass: per-record
            # flush/fsync deferred, every group's ack deferred past the
            # commit so ack-after-durability still holds
            done: List[int] = []
            with wal.batched():
                for g in groups:  # zt-lint: disable=ZT09 — per coalesced GROUP (one device step each)
                    done.extend(self._flush_group(g, plans))
            self._ack_done(done, plans)
        else:
            for g in groups:  # zt-lint: disable=ZT09 — per coalesced GROUP (one device step each)
                self._ack_done(self._flush_group(g, plans), plans)
        # payloads with no device chunks at all (every span boundary-
        # sampled away, or an empty payload): nothing to group, ack now
        # zt-lint: disable=ZT09 — per completed PAYLOAD, dict reads only
        empty = [
            pid for pid, p in plans.items()
            if p["left"] == 0 and not p.get("acked")
        ]
        if empty:
            self._ack_done(empty, plans)

    def _flush_group(self, group: List[tuple], plans: Dict[int, dict]) -> List[int]:  # zt-dispatch-critical: one coalesced group -> one remap+step+WAL record
        store = self.store
        led = self._cp_ledger
        t_g0 = time.perf_counter()
        pairs = []
        if led is not None:
            seen: Set[int] = set()
            for _, pid in group:  # zt-lint: disable=ZT09 — per group member, set lookups only
                if pid not in seen:
                    seen.add(pid)
                    pairs.append((self._cslots.get(pid, -1), pid))
            # zt-lint: disable=ZT09 — per traced group MEMBER
            traced = [(s, p) for s, p in pairs if s >= 0]
            if len(traced) == 1:
                # arm the thread-local so wal.py's append/fsync stamps
                # land in this payload's timeline (WAL rides the step)
                _critpath.set_active(led, traced[0][0], traced[0][1])
            elif traced:
                _critpath.set_active_group(led, traced)
        n_spans = n_dur = n_err = 0
        lo = hi = None
        parts = []
        for e, pid in group:  # zt-lint: disable=ZT09 — per CHUNK (max_batch-sized); all per-span work inside is vectorized
            fused, c_spans, c_dur, c_err, ts_range, arch, rec, _c, is_view, widx = e
            if arch:
                self._archive(arch)  # zt-lint: disable=ZT09 — per archive SLICE = the 1-in-N sampled raw spans; decode/gate IS the retention surface, bounded by the sampling rate
            if rec is not None and getattr(store, "_disk", None) is not None:
                # sampling gate: the fused sketch feed below always sees
                # 100% of spans; only raw-archive retention is gated.
                # Gating happens here (not in disk_append_record) so the
                # sync fast path is not double-gated, and at flush time
                # so verdicts see the same publish state as the serial
                # path's dispatch-ordered gate.
                sampler = store.agg.sampler
                if sampler is not None:
                    rec = sampler.gate_record(rec)  # zt-lint: disable=ZT09 — vectorized verdict; the per-kept-span byte compaction runs only when spans are gated away, on ONE record
                if rec is not None:
                    store.disk_append_record(rec)
            if self.shadow is not None:
                # the tap may retain its argument: never hand it a live
                # ring-slot view
                self.shadow.offer_fused(
                    np.array(fused) if is_view else fused
                )
            m = self._maps[widx]
            parts.append((fused, m.svc, m.key))
            n_spans += c_spans
            n_dur += c_dur
            n_err += c_err
            if c_spans > 0:
                lo = ts_range[0] if lo is None else min(lo, ts_range[0])
                hi = ts_range[1] if hi is None else max(hi, ts_range[1])
        if len(group) == 1:
            ts = group[0][0][4]  # the chunk's own range, bit-for-bit
        else:
            ts = (lo, hi) if lo is not None else (0, 0)
        tf0 = time.perf_counter()
        # resource-fault injection (faults.py, ISSUE 13/18): an armed
        # feed.latency site sleeps here — the exact seam where a slow
        # device feed stalls the dispatcher — so overload tests can
        # manufacture queue saturation deterministically. The group's
        # tenant is passed explicitly (the dispatcher thread has no
        # request context) so a tenant-scoped fault stalls only that
        # tenant's dispatches.
        g_tidx = self._tenant_of.get(group[0][1], 0) if group else 0
        faults.resource_point(
            "feed.latency",
            tenant=self._tenant_names[g_tidx]
            if 0 <= g_tidx < len(self._tenant_names) else "default",
        )
        store.agg.ingest_fused_multi(
            parts, n_spans=n_spans, n_dur=n_dur, n_err=n_err,
            ts_range=ts, pad_to_multiple=store._pad,
        )
        tf1 = time.perf_counter()
        obs.record("mp_device_feed", tf1 - tf0)
        if led is not None:
            for s, p in pairs:  # zt-lint: disable=ZT09 — per traced group member, 3 word stores each
                if s >= 0:
                    led.stamp(
                        s, _critpath.SEG_DEVICE_FEED,
                        int(tf0 * 1e9), int(tf1 * 1e9), p,
                    )
            _critpath.clear_active()
        if len(group) > 1:
            self.counters["coalescedBatches"] += 1
            self.counters["coalescedChunks"] += len(group)
        # apportion this group's flush wall across its chunks by span
        # weight, so mp_record stays a PER-CHUNK handling time (consume
        # + attributable flush share) like the pre-ring tier's stage —
        # not the whole pass wall billed to every payload in it
        g_wall = time.perf_counter() - t_g0
        # zt-lint: disable=ZT09 — per group MEMBER (bounded by
        # coalesce_max), integer header reads only
        g_spans = sum(e[1] for e, _ in group) or len(group)
        done = []
        for e, pid in group:  # zt-lint: disable=ZT09 — per group member, dict bookkeeping only
            p = plans[pid]
            p["flush_s"] = p.get("flush_s", 0.0) + g_wall * (
                (e[1] or 1) / g_spans
            )
            p["left"] -= 1
            if p["left"] == 0:
                done.append(pid)
        return done

    def _ack_done(self, pids: List[int], plans: Dict[int, dict]) -> None:  # zt-dispatch-critical: post-durability ack fan-in on the dispatch core — O(payloads per pass)
        """Ack payloads whose last chunk is durable: counters, metrics,
        ledger ack, inflight release. Runs after the group flush — and
        after the vectored WAL commit when one covered the pass."""
        for pid in pids:  # zt-lint: disable=ZT09 — per completed PAYLOAD, counter updates only
            p = plans[pid]
            if p.get("acked"):
                continue
            p["acked"] = True
            total = p["spans"]
            dropped = p["dropped"]
            obs.record(
                "mp_record", p["consume_s"] + p.get("flush_s", 0.0)
            )
            self.counters["accepted"] += total
            self.counters["sampleDropped"] += max(dropped, 0)
            if self.metrics is not None:
                self.metrics.increment_spans(total + max(dropped, 0))
                if dropped > 0:
                    self.metrics.increment_spans_dropped(dropped)
            cs = (
                self._cslots.get(pid, -1)
                if self._cp_ledger is not None else -1
            )
            if cs >= 0:
                # durable ack: the WAL append + device feed completed
                self._cp_ledger.ack(cs, pid)
            # per-tenant acked accounting + the retained-spans budget
            # feed (ISSUE 18): span counts are only known post-parse,
            # so retention budgets charge here, at ack time
            tidx = self._tenant_of.get(pid, 0)
            tname = (
                self._tenant_names[tidx]
                if 0 <= tidx < len(self._tenant_names) else "default"
            )
            ta = self._tenant_acked.setdefault(
                tname, {"payloads": 0, "spans": 0}
            )
            ta["payloads"] += 1
            ta["spans"] += total
            sink = self.tenant_sink
            if sink is not None and total:
                try:
                    sink(tname, total)
                except Exception:  # accounting must never kill an ack
                    logger.exception("tenant_sink failed")
            self._finish(pid)

    # -- worker death -----------------------------------------------------

    def _check_liveness(self, eof_set: set) -> None:
        """A worker that died uncleanly (segfault in the native parser,
        OOM kill) never sends EOF: without this check its inflight
        payloads would pin _inflight > 0 and drain()/stop() would wedge
        forever (ADVICE r3)."""
        dead = [
            w
            for w, p in enumerate(self._procs)
            if not p.is_alive() and w not in eof_set
        ]
        if dead:
            self._reap_dead_workers(dead, eof_set)

    def _reap_dead_workers(self, dead: List[int], eof_set: set) -> None:
        """A worker died without EOF. Recover EVERYTHING and keep the
        pool serving on the survivors: because chunk application is
        buffered until a payload's completion marker, a half-processed
        payload has mutated no store state — its buffered chunks are
        discarded, its ring stripe reclaimed (the pid-guarded torn-slot
        reset handles a SIGKILL mid-write), and the whole payload (plus
        everything queued behind it) re-ingests on the slow path. Zero
        acked-span loss, no double-ingest, and the dead worker's
        _IdMaps / inflight accounting are released. Re-entrancy:
        draining below can discover ANOTHER premature EOF — those fold
        into THIS reap via _reap_extra rather than recursing (ADVICE
        r4)."""
        self._reaping = True
        try:
            # mark dead under _cv FIRST: submit() registers under the
            # same lock, so after this no new payload can target these
            # workers, and every already-registered one is visible to
            # the refeed scan below
            with self._cv:
                self._dead.update(dead)
            # timeout-based drains, not get_nowait(): mp.Queue puts go
            # through a feeder thread, so a just-shipped result can be
            # in the pipe but not yet visible — get_nowait() would miss
            # chunks a surviving worker already produced
            while True:
                try:
                    msg = self._result_q.get(timeout=0.25)
                except queue.Empty:
                    break
                self._route_msg(msg, eof_set)
            # apply + FLUSH everything already produced (survivors, and
            # any payload the dead workers fully published before
            # dying): completed payloads leave _pending before the
            # refeed scan, so they cannot double-ingest
            ready: List[tuple] = []
            consumed: Dict[int, int] = {}
            self._pump(ready, consumed)
            if ready:
                self._flush_ready(ready)
            self._materialize_views()
            for w, cnt in consumed.items():
                for _ in range(cnt):
                    self._ring.free_next(w)
            if self._reap_extra:
                with self._cv:
                    self._dead.update(self._reap_extra)
                dead = dead + [w for w in self._reap_extra if w not in dead]
                self._reap_extra = []
            refed = 0
            for w in dead:
                eof_set.add(w)
                self._pending_eof.discard(w)
                self._maps[w] = None  # free the dead worker's id tables
                self._holdback[w].clear()
                rec = self._ring.reclaim_stripe(
                    w, self._procs[w].pid or -1
                )
                self.counters["ringDiscarded"] += rec["discarded"]
                self.counters["ringTorn"] += rec["torn"]
                # empty its queue so the feeder thread can't block
                # shutdown; the payloads themselves re-ingest via the
                # _assigned scan (they are all still in _pending)
                while True:
                    try:
                        item = self._work_qs[w].get(timeout=0.25)
                    except queue.Empty:
                        break
                    del item
                with self._cv:
                    owned = [
                        p for p, a in self._assigned.items() if a == w
                    ]
                for pid in owned:
                    self._buffered.pop(pid, None)
                    payload = self._pending.get(pid)
                    if payload is None:
                        continue
                    # the dead worker's ledger slots would stay OPEN
                    # forever: recycle them now (no stuck timelines)
                    self._drop_cslot(pid)
                    self._fallback(payload)
                    self.counters["fallbacks"] += 1
                    self._finish(pid)
                    refed += 1
        finally:
            self._reaping = False
        logger.warning(
            "mp-ingest worker(s) %s died uncleanly; %d acked payload(s) "
            "re-ingested via the slow path, pool continues on %d "
            "survivor(s)",
            dead, refed, self.workers - len(self._dead),
        )

    # -- shared helpers ----------------------------------------------------

    def _drop_cslot(self, pid: int) -> None:
        """Abandon a payload's timeline (fallback/reap path): partial
        stamps would decompose misleadingly, so the slot recycles now."""
        if self._cp_ledger is None:
            return
        with self._cv:
            cs = self._cslots.pop(pid, -1)
        if cs >= 0:
            self._cp_ledger.abandon(cs)

    def _finish(self, pid: int) -> None:
        with self._cv:
            self._pending.pop(pid, None)
            w = self._assigned.pop(pid, None)
            self._cslots.pop(pid, None)
            self._tenant_of.pop(pid, None)
            if w is not None and self._qdepth[w] > 0:
                self._qdepth[w] -= 1
            self._inflight -= 1
            if self._inflight == 0:
                self._cv.notify_all()

    def _archive(self, slices: List[bytes]) -> None:
        from zipkin_tpu.tpu.store import _decode_raw_span

        spans = []
        for raw in slices:
            try:
                spans.append(_decode_raw_span(raw))
            except Exception:  # slice the strict codec rejects: skip
                continue
        if not spans:
            return
        sampler = self.store.agg.sampler
        if sampler is not None:
            # the RAM-archive sample is a retention surface like the disk
            # archive: gate it with the same verdicts (re-packing the few
            # 1-in-N sampled spans is cheap; interning is idempotent)
            from zipkin_tpu.tpu.columnar import pack_spans

            with self.store._intern_lock:
                cols = pack_spans(spans, self.store.vocab, 1)
            keep = sampler.verdict_cols(cols)[: len(spans)]
            spans = [s for s, k in zip(spans, keep) if k]
        if spans:
            self.store._archive.accept(spans).execute()

    def _fallback(self, payload: bytes) -> None:
        """Payloads the native parser rejects — or that a dead worker
        owned — take the object path, including the boundary sampler, so
        a parser punt cannot smuggle unsampled spans into the store.
        Malformed payloads are counted and dropped (the asynchronous-ack
        trade: like the reference's Kafka collector, a poison message
        can't be HTTP-400'd after the 202 — SURVEY.md §3.3). The codec
        sniffs the wire format, so proto3 payloads fall back too."""
        from zipkin_tpu.model import codec

        try:
            spans = codec.decode_spans(payload)
        except Exception:
            logger.warning("mp-ingest: undecodable payload dropped")
            if self.metrics is not None:
                self.metrics.increment_messages_dropped()
            return
        n_all = len(spans)
        if self._sampler is not None:
            spans = [s for s in spans if self._sampler.test(s)]
        self.store.accept(spans).execute()
        if self.metrics is not None:
            self.metrics.increment_spans(n_all)
            if n_all - len(spans):
                self.metrics.increment_spans_dropped(n_all - len(spans))
