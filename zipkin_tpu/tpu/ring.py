"""Shared-memory span ring: the fan-out tier's producer/consumer seam.

ISSUE 16 replaces the per-chunk record/replay handoff (per-worker shm
slabs + a pickled metadata message per chunk through ``result_q``) with
one fixed-slot ring: parse workers write the packed columnar wire image
AND the chunk's sidecar (vocab-journal delta, archive slices, disk
record) directly into a ring slot, and the dispatcher drains contiguous
runs of ready slots — consuming the image as a zero-copy view into the
slot until the coalesced device flush gathers it.

Topology: the ring is striped by producer. Worker ``w`` owns slots
``w*S .. w*S+S-1`` (S = ``stripe_slots``) and claims them strictly in
order, so each stripe is a single-producer/single-consumer ring with a
lock-free (head, tail) pair: the head advances only on the owning
worker's publish, the tail only on the dispatcher's free. No cross-
process lock exists anywhere on the claim/publish/consume path — which
is exactly what makes the ring survive a SIGKILL'd producer: there is
no lock a dying worker can take to its grave.

Slot lifecycle (seqlock-stamped, the obs/recorder + critpath idiom):

- ``claim`` (worker): generation bumped to ODD, state WRITING, pid
  recorded. The head does NOT move yet — an unpublished slot is
  invisible to the consumer.
- ``publish`` (worker): header fields written, generation bumped to
  EVEN, state READY, then the stripe head advances. The head is the
  release fence: the dispatcher only looks at slots below it.
- ``free`` (dispatcher): state FREE, tail advances.
- ``reclaim_stripe`` (dispatcher, pid-guarded): a worker that died
  uncleanly leaves READY slots the reaper discards (their payloads
  re-ingest whole via the fallback path — consuming a dead worker's
  chunks could double-apply against the refeed) and, at the head
  position, possibly one TORN slot: generation odd, state WRITING,
  owner pid dead. Both are reset; nothing acked is lost because
  nothing is acked until the dispatcher's flush applies it.

Backpressure: a worker whose stripe is full blocks in ``claim`` (the
ring_wait critpath segment); ``occupancy()`` is the submit-side gauge
that converts the tier's 429/RESOURCE_EXHAUSTED contract from queue
depth to ring occupancy.

This module is imported by spawn workers: numpy + stdlib only, no jax.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

import numpy as np

RING_MAGIC = 0x53525247  # 'SRRG'

# header words (int64): [magic, n_workers, stripe_slots, img_cap_u32,
#                        aux_cap, slot_bytes, pad, pad]
_HDR_WORDS = 8
# per-stripe control words: [head, tail]
_CTL_WORDS = 2

# slot header (int64 words); the image and aux regions follow at fixed
# byte offsets inside the slot
_S_GEN = 0        # seqlock generation: odd while the owner writes
_S_STATE = 1      # FREE / WRITING / READY
_S_PID = 2        # owner process id (the reclaim guard)
_S_PIDX = 3       # payload id (dispatcher _pending key)
_S_WSEQ = 4       # per-worker chunk sequence (cross-channel ordering)
_S_PER = 5        # per-shard lane count of the image
_S_NSPANS = 6
_S_NDUR = 7
_S_NERR = 8
_S_DROPPED = 9    # -1 = continuation chunk
_S_CSLOT = 10     # critpath ledger slot (-1 untraced)
_S_TS_MIN = 11
_S_TS_MAX = 12
_S_PARSE_NS = 13
_S_PACK_NS = 14
_S_ROUTE_NS = 15
_S_AUX_LEN = 16
_S_PUBLISH_NS = 17
_S_TENANT = 18    # tenant intern idx (ISSUE 18); 0 = default tenant
SLOT_HDR_WORDS = 19

ST_FREE, ST_WRITING, ST_READY = 0, 1, 2

_ALIGN = 64


def _align(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SpanRing:
    """Owner (dispatcher-process) side of the striped span ring.

    ``img_cap_u32`` is the worst-case fused-image word count of one
    chunk; ``aux_cap`` bounds the pickled sidecar. A chunk whose sidecar
    outgrows ``aux_cap`` does not deadlock the ring — the worker routes
    it through the queue fallback instead (mp_ingest ``_KIND_BATCH_OBJ``).
    """

    def __init__(
        self,
        n_workers: int,
        stripe_slots: int,
        img_cap_u32: int,
        aux_cap: int = 1 << 18,
        *,
        name: Optional[str] = None,
    ) -> None:
        from multiprocessing import shared_memory

        self.n_workers = int(n_workers)
        self.stripe_slots = int(stripe_slots)
        self.img_cap_u32 = int(img_cap_u32)
        self.aux_cap = int(aux_cap)
        self.slot_bytes = _align(
            SLOT_HDR_WORDS * 8 + self.img_cap_u32 * 4 + self.aux_cap
        )
        self._ctl_base = _HDR_WORDS
        self._slots_off = _align(
            (self._ctl_base + _CTL_WORDS * self.n_workers) * 8
        )
        total = self._slots_off + (
            self.n_workers * self.stripe_slots * self.slot_bytes
        )
        if name is None:
            self._shm = shared_memory.SharedMemory(create=True, size=total)
            self._owner = True
        else:
            self._shm = shared_memory.SharedMemory(name=name)
            self._owner = False
        self._a = np.frombuffer(
            self._shm.buf, np.int64, count=self._slots_off // 8
        )
        if self._owner:
            self._a[:] = 0
            self._a[0] = RING_MAGIC
            self._a[1] = self.n_workers
            self._a[2] = self.stripe_slots
            self._a[3] = self.img_cap_u32
            self._a[4] = self.aux_cap
            self._a[5] = self.slot_bytes
        self._closed = False

    # -- attach plumbing --------------------------------------------------

    def params(self) -> dict:
        """Spawn-safe attach info for :class:`RingProducer`."""
        return {
            "name": self._shm.name,
            "n_workers": self.n_workers,
            "stripe_slots": self.stripe_slots,
            "img_cap_u32": self.img_cap_u32,
            "aux_cap": self.aux_cap,
        }

    # -- addressing -------------------------------------------------------

    def _head(self, w: int) -> int:
        return int(self._a[self._ctl_base + _CTL_WORDS * w])

    def _tail(self, w: int) -> int:
        return int(self._a[self._ctl_base + _CTL_WORDS * w + 1])

    def _set_tail(self, w: int, v: int) -> None:
        self._a[self._ctl_base + _CTL_WORDS * w + 1] = v

    def _slot_base(self, w: int, seq: int) -> int:
        g = w * self.stripe_slots + (seq % self.stripe_slots)
        return self._slots_off + g * self.slot_bytes

    def _hdr(self, byte_base: int) -> np.ndarray:
        return np.frombuffer(
            self._shm.buf, np.int64, count=SLOT_HDR_WORDS, offset=byte_base
        )

    def image(self, w: int, seq: int, count: int) -> np.ndarray:
        """u32 view of a slot's image region (zero-copy into shm)."""
        return np.frombuffer(
            self._shm.buf, np.uint32, count=count,
            offset=self._slot_base(w, seq) + SLOT_HDR_WORDS * 8,
        )

    def aux(self, w: int, seq: int, length: int) -> bytes:
        base = self._slot_base(w, seq) + SLOT_HDR_WORDS * 8 + (
            self.img_cap_u32 * 4
        )
        return bytes(self._shm.buf[base:base + length])

    # -- consumer side (dispatcher only) ----------------------------------

    def peek(self, w: int, ahead: int = 0):
        """``(header_copy, seq)`` of stripe ``w``'s next unconsumed slot
        (``ahead`` slots past the tail — the dispatcher's drain pass
        consumes several slots before freeing any), or None. A published
        slot is complete by construction (the head is the release
        fence), so a READY state with an even generation below the head
        cannot be torn."""
        seq = self._tail(w) + ahead
        if seq >= self._head(w):
            return None
        hdr = self._hdr(self._slot_base(w, seq)).copy()
        if hdr[_S_STATE] != ST_READY or hdr[_S_GEN] % 2:
            return None  # pragma: no cover - head fence makes this unreachable
        return hdr, seq

    def free_next(self, w: int) -> None:
        """Consume stripe ``w``'s tail slot (dispatcher has fully used
        the image view; the region may be overwritten by the producer)."""
        t = self._tail(w)
        hdr = self._hdr(self._slot_base(w, t))
        hdr[_S_STATE] = ST_FREE
        self._set_tail(w, t + 1)

    def reclaim_stripe(self, w: int, dead_pid: int = -1) -> dict:
        """Reset a dead worker's stripe (dispatcher only). Discards
        published-but-unconsumed slots and the torn WRITING slot a
        mid-write SIGKILL leaves at the head. ``dead_pid`` guards the
        torn-slot reset: a slot claimed by any OTHER pid (a stale
        header from a previous owner) is reset too, but counted apart
        so tests can assert the torn case precisely."""
        t, h = self._tail(w), self._head(w)
        discarded = 0
        for seq in range(t, h):
            self._hdr(self._slot_base(w, seq))[_S_STATE] = ST_FREE
            discarded += 1
        torn = 0
        hdr = self._hdr(self._slot_base(w, h))
        if hdr[_S_STATE] == ST_WRITING and hdr[_S_GEN] % 2:
            if dead_pid < 0 or int(hdr[_S_PID]) == dead_pid:
                torn = 1
            hdr[_S_GEN] += 1  # re-even the generation for the next owner
            hdr[_S_STATE] = ST_FREE
        self._set_tail(w, h)
        return {"discarded": discarded, "torn": torn}

    # -- gauges -----------------------------------------------------------

    @property
    def capacity(self) -> int:
        return self.n_workers * self.stripe_slots

    def stripe_depth(self, w: int) -> int:
        return self._head(w) - self._tail(w)

    def stripe_full(self, w: int) -> bool:
        return self.stripe_depth(w) >= self.stripe_slots

    def occupancy(self) -> int:
        return sum(self.stripe_depth(w) for w in range(self.n_workers))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._a = None
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass


class RingProducer:
    """Worker-process half: claim -> write image/aux -> publish.

    Single producer per stripe; every mutation is plain word stores on
    the mapped buffer, so a SIGKILL at any instruction leaves at most
    one torn slot (odd generation) that ``reclaim_stripe`` resets."""

    def __init__(self, params: dict, widx: int) -> None:
        from multiprocessing import shared_memory

        self.widx = int(widx)
        self.stripe_slots = int(params["stripe_slots"])
        self.img_cap_u32 = int(params["img_cap_u32"])
        self.aux_cap = int(params["aux_cap"])
        n_workers = int(params["n_workers"])
        self.slot_bytes = _align(
            SLOT_HDR_WORDS * 8 + self.img_cap_u32 * 4 + self.aux_cap
        )
        self._shm = shared_memory.SharedMemory(name=params["name"])
        self._ctl_base = _HDR_WORDS
        self._slots_off = _align((_HDR_WORDS + _CTL_WORDS * n_workers) * 8)
        self._a = np.frombuffer(
            self._shm.buf, np.int64, count=self._slots_off // 8
        )
        self._wseq = 0  # per-worker chunk sequence (cross-channel order)

    def next_wseq(self) -> int:
        """Allocate the next chunk sequence number; also consumed by the
        queue-fallback path so ring and queue chunks stay totally
        ordered per worker."""
        s = self._wseq
        self._wseq += 1
        return s

    def _head(self) -> int:
        return int(self._a[self._ctl_base + _CTL_WORDS * self.widx])

    def _advance_head(self) -> None:
        self._a[self._ctl_base + _CTL_WORDS * self.widx] += 1

    def _tail(self) -> int:
        return int(self._a[self._ctl_base + _CTL_WORDS * self.widx + 1])

    def _slot_base(self, seq: int) -> int:
        g = self.widx * self.stripe_slots + (seq % self.stripe_slots)
        return self._slots_off + g * self.slot_bytes

    def _hdr(self, byte_base: int) -> np.ndarray:
        return np.frombuffer(
            self._shm.buf, np.int64, count=SLOT_HDR_WORDS, offset=byte_base
        )

    def try_claim(self) -> bool:
        """Claim the next stripe slot if the stripe has room. The slot
        is marked WRITING with an odd generation + this pid before any
        payload byte lands (the torn-write fence)."""
        seq = self._head()
        if seq - self._tail() >= self.stripe_slots:
            return False
        hdr = self._hdr(self._slot_base(seq))
        if hdr[_S_GEN] % 2 == 0:
            hdr[_S_GEN] += 1  # odd: mid-write
        hdr[_S_STATE] = ST_WRITING
        hdr[_S_PID] = os.getpid()
        return True

    def claim(self, poll_s: float = 0.0002, max_poll_s: float = 0.01) -> float:
        """Blocking claim; returns the seconds spent waiting for a free
        slot (the worker's ring_wait critpath segment).

        The poll interval backs off exponentially to ``max_poll_s``: a
        stripe stays full for as long as one device step takes, and on
        shared-core hosts N workers re-polling a full stripe every
        0.2 ms steal enough scheduler quanta from the dispatcher's XLA
        compute to visibly stretch the very step they are waiting on
        (no condvar can live in the shm segment, so a backed-off poll
        is the wake mechanism)."""
        t0 = time.perf_counter()
        wait = poll_s
        while not self.try_claim():
            time.sleep(wait)
            wait = min(wait * 2, max_poll_s)
        return time.perf_counter() - t0

    def image(self, count: int) -> np.ndarray:
        """Writable u32 view of the CLAIMED slot's image region."""
        if count > self.img_cap_u32:
            raise ValueError(
                f"image of {count} u32 words exceeds the slot capacity "
                f"({self.img_cap_u32}); route the chunk through the "
                "result queue instead"
            )
        return np.frombuffer(
            self._shm.buf, np.uint32, count=count,
            offset=self._slot_base(self._head()) + SLOT_HDR_WORDS * 8,
        )

    def publish(
        self,
        *,
        pidx: int,
        wseq: int,
        per: int,
        n_spans: int,
        n_dur: int,
        n_err: int,
        dropped: int,
        cslot: int,
        ts_min: int,
        ts_max: int,
        parse_ns: int,
        pack_ns: int,
        route_ns: int,
        aux: bytes,
        tenant: int = 0,
    ) -> None:
        """Fill the claimed slot's header + aux and make it visible:
        generation re-evened, state READY, then the head fence moves."""
        if len(aux) > self.aux_cap:
            raise ValueError(
                f"sidecar of {len(aux)} bytes exceeds the slot aux "
                f"capacity ({self.aux_cap}); route the chunk through "
                "the result queue instead"
            )
        base = self._slot_base(self._head())
        if aux:
            off = base + SLOT_HDR_WORDS * 8 + self.img_cap_u32 * 4
            self._shm.buf[off:off + len(aux)] = aux
        hdr = self._hdr(base)
        hdr[_S_PIDX] = pidx
        hdr[_S_WSEQ] = wseq
        hdr[_S_PER] = per
        hdr[_S_NSPANS] = n_spans
        hdr[_S_NDUR] = n_dur
        hdr[_S_NERR] = n_err
        hdr[_S_DROPPED] = dropped
        hdr[_S_CSLOT] = cslot
        hdr[_S_TS_MIN] = ts_min
        hdr[_S_TS_MAX] = ts_max
        hdr[_S_PARSE_NS] = parse_ns
        hdr[_S_PACK_NS] = pack_ns
        hdr[_S_ROUTE_NS] = route_ns
        hdr[_S_AUX_LEN] = len(aux)
        hdr[_S_PUBLISH_NS] = time.perf_counter_ns()
        hdr[_S_TENANT] = tenant
        hdr[_S_GEN] += 1  # even: contents complete
        hdr[_S_STATE] = ST_READY
        self._advance_head()

    def close(self) -> None:
        self._a = None
        self._shm.close()


def pack_aux(svc_new, name_new, pairs_new, arch, rec) -> bytes:
    """Serialize a chunk's sidecar for the slot aux region."""
    return pickle.dumps(
        (svc_new, name_new, pairs_new, arch, rec), protocol=4
    )


def unpack_aux(raw: bytes):
    return pickle.loads(raw)
