"""Checkpoint/resume of device sketch state.

The reference has no in-process durability — it delegates to storage
backends and replays from Kafka offsets (SURVEY.md §5 checkpoint row).
The TPU tier's aggregates live in volatile HBM, so durability is
explicit here: pull the sharded state to host, write one ``.npz`` plus
the string vocabularies as JSON, restore on boot.

Crash consistency (ISSUE 3): a snapshot is TWO files, and a crash
between their renames must never pair a new state with an old meta
(the old meta's wal_seq would double-replay batches the new state
already holds). The commit protocol makes ``meta.json`` the single
atomic commit point:

1. the state is written to a fresh generation-named file
   (``sketch_state-<gen>.npz``), fsynced, renamed in, dir fsynced —
   the previous generation is untouched;
2. a per-generation meta sidecar (``sketch_state-<gen>.meta.json``,
   same content) is committed the same way — it is what makes the
   generation independently restorable after meta.json moves on;
3. ``meta.json`` (which names its state file) is written the same way —
   ``os.replace`` flips the snapshot from old pair to new pair in one
   atomic step;
4. only then are generations older than the newest K pruned.

A crash at any instant (the ``snapshot.post_state`` / ``post_meta``
crashpoints in zipkin_tpu.faults pin the two worst ones) leaves
meta.json referencing one COMPLETE state file. fsync before each
rename is what makes the rename itself crash-durable: a rename of
unflushed data can survive a power cut while the bytes do not.

Bit-rot tolerance (ISSUE 7): crash consistency says nothing about a
snapshot that went bad AT REST — a flipped bit in the newest state
file used to pass shape/dtype validation and silently poison every
aggregate, unrecoverably (older generations were pruned, covered WAL
deleted). Three mechanisms close that:

- **Integrity manifest**: the meta records a crc32 per serialized
  state leaf (``leaf_crcs``); restore recomputes and refuses a
  mismatching generation instead of loading it.
- **K-generation retention + lossless fallback**: the newest
  ``keep_generations`` (default 2) intact generations are retained at
  commit, and the WAL truncation floor is the OLDEST retained
  generation's wal_seq (``retained_coverage``). A damaged generation
  is quarantined (``.quarantine`` rename — never unlinked, it is
  postmortem evidence) and restore falls back to the previous one,
  replaying the longer WAL suffix — zero acked-span loss,
  bit-identical to a boot that never saw the corruption.
- The ``snapshot.state`` corrupt site (zipkin_tpu.faults) damages the
  just-committed generation deterministically so the fallback path is
  soak-tested, and the background scrubber (runtime/scrub.py)
  re-verifies retained generations at rest.

Replay markers: the snapshot records ingest counters; transports that
support offsets (replay files, Kafka) can resume from
``counters["spans"]`` — the analog of Kafka consumer-offset resume.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import time
import zlib
from typing import TYPE_CHECKING, List, Optional

import numpy as np

from zipkin_tpu import faults

if TYPE_CHECKING:  # pragma: no cover
    from zipkin_tpu.tpu.store import TpuStorage

logger = logging.getLogger(__name__)

STATE_FILE = "sketch_state.npz"  # legacy single-generation name (read-only)
META_FILE = "meta.json"
_STATE_PREFIX = "sketch_state-"
QUARANTINE_SUFFIX = ".quarantine"
# how many intact generations a commit retains (the fallback depth);
# overridable per store via `store.snapshot_keep` / TPU_SNAPSHOT_KEEP
DEFAULT_KEEP_GENERATIONS = 2

# Bump whenever the AggState pytree or the config serialization changes
# shape (ADVICE r2: v1 silently covered two incompatible layouts and
# restore failures misattributed the cause to operator config changes).
# v2 = r2 retention layout (hist_t/rollup leaves, retention config keys).
# v3 = sampling tier (s_rate/s_tail/s_link tables, r_keep ring column,
#      sampling/sample_rare_min config keys).
# v4 = persistent incremental link ctx (ctx_* leaves: sorted union
#      order/keys/runs/safe-candidates + resolved tree + watermark
#      cursor) — resumed ctx must be bit-identical, so it rides the
#      snapshot like every other leaf.
# v5 = time-disaggregated sketch tier (tb_* current-bucket leaves +
#      pend_ep bucket tags, time_buckets/time_bucket_minutes/
#      time_digest_centroids config keys) — tpu/timetier.py.
SNAPSHOT_VERSION = 5


def _fsync_dir(directory: str) -> None:
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _state_generations(directory: str):
    """[(gen, filename)] for every generation-named state file, sorted.
    Quarantined generations (``.npz.quarantine``) are excluded — they
    are evidence, not candidates. A directory that does not exist yet
    (no snapshot ever committed) simply has no generations."""
    out = []
    try:
        names = os.listdir(directory)
    except FileNotFoundError:
        return out
    for name in names:
        if name.startswith(_STATE_PREFIX) and name.endswith(".npz"):
            try:
                out.append((int(name[len(_STATE_PREFIX):-4]), name))
            except ValueError:
                continue
    out.sort()
    return out


def _gen_meta_name(state_name: str) -> str:
    """sketch_state-<gen>.npz -> sketch_state-<gen>.meta.json"""
    return state_name[:-4] + ".meta.json"


def _next_generation(directory: str) -> int:
    """One past the highest generation number ever used — quarantined
    generations count, so a new state file never reuses the name a
    quarantined ``.npz.quarantine`` sibling was renamed from."""
    top = 0
    for name in os.listdir(directory):
        stem = name
        if stem.endswith(QUARANTINE_SUFFIX):
            stem = stem[: -len(QUARANTINE_SUFFIX)]
        if stem.startswith(_STATE_PREFIX) and stem.endswith(".npz"):
            try:
                top = max(top, int(stem[len(_STATE_PREFIX):-4]))
            except ValueError:
                continue
    return top + 1


def _write_atomic(directory: str, name: str, text: str) -> None:
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, name))
    _fsync_dir(directory)


def _quarantine(path: str) -> bool:
    """Rename ``path`` aside with the quarantine suffix — NEVER unlink:
    a quarantined artifact is the postmortem evidence of what rotted."""
    try:
        # zt-lint: disable=ZT12 — quarantine moves already-corrupt bytes ASIDE; the poison file's durability is not a recovery invariant (a lost rename just re-quarantines next boot)
        os.replace(path, path + QUARANTINE_SUFFIX)
        return True
    except OSError:
        return False


def quarantine_generation(directory: str, state_name: str) -> None:
    """Move one generation (state file + its meta sidecar) aside."""
    quarantined = _quarantine(os.path.join(directory, state_name))
    _quarantine(os.path.join(directory, _gen_meta_name(state_name)))
    if quarantined:
        logger.warning(
            "snapshot generation %s quarantined (-> %s%s)",
            state_name, state_name, QUARANTINE_SUFFIX,
        )


def leaf_digests(arrays: List[np.ndarray]) -> List[int]:
    """crc32 per serialized state leaf — the integrity manifest."""
    return [
        zlib.crc32(np.ascontiguousarray(a).tobytes()) for a in arrays
    ]


def save(
    store: "TpuStorage", directory: str, keep: Optional[int] = None
) -> str:
    """Snapshot sketches + vocab into ``directory`` (atomic). Returns path."""
    os.makedirs(directory, exist_ok=True)
    if keep is None:
        keep = getattr(store, "snapshot_keep", DEFAULT_KEEP_GENERATIONS)
    keep = max(1, int(keep))
    # consistency: the state is CLONED on device under the aggregator
    # lock together with wal_seq AND the host counters (so "state +
    # counters + everything after wal_seq" describe the same instant),
    # then pulled to host lock-free — holding the lock through the pull
    # would stall ingest for the whole transfer (concurrent steps donate
    # the live buffers, but the clone's are independent).
    clone, wal_seq, counters = store.agg.state_clone()
    arrays = {f"f{i}": np.asarray(leaf) for i, leaf in enumerate(clone)}

    # stray temp files from a crashed earlier save are dead weight
    for name in os.listdir(directory):
        if name.endswith(".tmp"):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass

    gen = _next_generation(directory)
    state_name = f"{_STATE_PREFIX}{gen:08d}.npz"
    # disk-exhaustion site (ISSUE 13): fires BEFORE any rename, so an
    # ENOSPC save leaves every retained generation intact — the caller
    # (storage/tpu.py) flags durability at-risk and retries next cycle
    faults.resource_point("snapshot")
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:  # file object: savez won't append ".npz"
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    state_path = os.path.join(directory, state_name)
    os.replace(tmp, state_path)
    _fsync_dir(directory)
    faults.crashpoint("snapshot.post_state")

    meta = {
        "version": SNAPSHOT_VERSION,
        "saved_at": time.time(),
        "wal_seq": wal_seq,
        "state_file": state_name,
        # integrity manifest: crc32 per serialized leaf, verified on
        # every restore and by the at-rest scrubber — shape/dtype
        # validation alone cannot see a flipped bit
        "digest": "crc32",
        "leaf_crcs": leaf_digests([arrays[f"f{i}"] for i in range(len(arrays))]),
        "n_shards": store.agg.n_shards,
        "config": dataclasses.asdict(store.config),
        # agg counters from the locked capture; vocab-overflow counters
        # are monotonic, not restored by maybe_restore, and harmless to
        # read late — so the lock-free merge is safe
        "counters": {**store.ingest_counters(), **counters},
        "services": store.vocab.services._names,
        "span_names": store.vocab.span_names._names,
        "keys": store.vocab._key_list,
    }
    meta_text = json.dumps(meta)
    # the per-generation sidecar first: once meta.json moves on to a
    # newer generation, this sidecar is the ONLY record of this
    # generation's wal_seq/digests — what makes fallback restorable
    _write_atomic(directory, _gen_meta_name(state_name), meta_text)
    _write_atomic(directory, META_FILE, meta_text)
    faults.crashpoint("snapshot.post_meta")
    # bit-rot injection site: the generation just committed is damaged
    # AT REST (process keeps running) — restore/scrub must catch it
    faults.corrupt_point(
        "snapshot.state", state_path, 0, os.path.getsize(state_path)
    )

    # the new pair is durable — generations older than the newest
    # ``keep`` (and the legacy un-generationed file, if this dir
    # predates the commit protocol) can go. Quarantined generations are
    # never touched: evidence, not garbage.
    for old_gen, name in _state_generations(directory)[:-keep]:
        for victim in (name, _gen_meta_name(name)):
            try:
                os.unlink(os.path.join(directory, victim))
            except OSError:
                pass
    try:
        os.unlink(os.path.join(directory, STATE_FILE))
    except OSError:
        pass
    return directory


def maybe_restore(store: "TpuStorage", directory: str) -> bool:
    """Restore state + vocab if a compatible snapshot exists.

    Fallback (ISSUE 7): candidates are tried newest-first — meta.json's
    generation, then every older retained generation through its
    per-generation meta sidecar. An integrity failure (missing state
    file, unreadable npz, leaf digest mismatch) quarantines that
    generation and falls back to the next; WAL replay from the older
    wal_seq then recovers the difference losslessly (truncate_covered
    keeps the WAL suffix back to the oldest retained generation). A
    COMPATIBILITY failure (version/config/shard/layout drift) stops the
    whole restore instead — older generations are necessarily at least
    as incompatible, and an intact-but-foreign snapshot is operator
    error, not rot."""
    meta_path = os.path.join(directory, META_FILE)
    if not os.path.exists(meta_path):
        return False
    candidates = []  # (meta dict, state_name) newest first
    primary_name = None
    try:
        with open(meta_path) as f:
            meta = json.load(f)
        primary_name = meta.get("state_file", STATE_FILE)
        candidates.append((meta, primary_name))
    except (OSError, ValueError):
        logger.warning(
            "snapshot at %s: meta.json unreadable; trying retained "
            "generations", directory,
        )
    primary_gen = None
    if primary_name and primary_name.startswith(_STATE_PREFIX):
        try:
            primary_gen = int(primary_name[len(_STATE_PREFIX):-4])
        except ValueError:
            pass
    for gen, name in reversed(_state_generations(directory)):
        if name == primary_name:
            continue
        if primary_gen is not None and gen > primary_gen:
            # newer than the commit point: the generation landed but its
            # meta.json flip did not — never restore past the commit
            continue
        gm = os.path.join(directory, _gen_meta_name(name))
        try:
            with open(gm) as f:
                candidates.append((json.load(f), name))
        except (OSError, ValueError):
            continue  # orphan (crash between state and sidecar commit)

    for i, (cand, state_name) in enumerate(candidates):
        outcome = _restore_one(store, directory, cand, state_name)
        if outcome == "ok":
            if i:
                stats = getattr(store, "restore_stats", None)
                if stats is not None:
                    stats["restoreFallbacks"] = (
                        stats.get("restoreFallbacks", 0) + 1
                    )
                logger.warning(
                    "snapshot restore fell back %d generation(s) to %s; "
                    "the WAL suffix past its wal_seq replays the rest",
                    i, state_name,
                )
            return True
        if outcome == "incompatible":
            return False
        # integrity failure: quarantine and fall back to the next
        quarantine_generation(directory, state_name)
        stats = getattr(store, "restore_stats", None)
        if stats is not None:
            stats["generationsQuarantined"] = (
                stats.get("generationsQuarantined", 0) + 1
            )
    return False


def _restore_one(
    store: "TpuStorage", directory: str, meta: dict, state_name: str
) -> str:
    """Try one generation; returns "ok", "incompatible", or "integrity"."""
    state_path = os.path.join(directory, state_name)
    if not os.path.exists(state_path):
        logger.warning(
            "snapshot at %s: meta references missing state file %s; "
            "ignoring", directory, os.path.basename(state_path),
        )
        return "integrity"
    if meta.get("version") != SNAPSHOT_VERSION:
        logger.warning(
            "snapshot at %s has format version %s (this build writes %s); "
            "ignoring — re-snapshot after the next ingest",
            directory, meta.get("version"), SNAPSHOT_VERSION,
        )
        return "incompatible"
    want = dataclasses.asdict(store.config)
    if meta.get("config") != want:
        logger.warning(
            "snapshot at %s was taken under a different AggConfig "
            "(operator config changed); ignoring", directory,
        )
        return "incompatible"
    if meta.get("n_shards") != store.agg.n_shards:
        logger.warning(
            "snapshot at %s has %s shards but this mesh has %s; ignoring",
            directory, meta.get("n_shards"), store.agg.n_shards,
        )
        return "incompatible"

    import jax

    try:
        # np.load of an npz reads through zipfile, which CRC-checks each
        # member — gross rot (truncation, zeroed ranges) surfaces here
        # as an exception rather than as garbage leaves
        loaded = np.load(state_path)
        leaves = [loaded[f"f{i}"] for i in range(len(loaded.files))]
    except Exception as e:
        logger.warning(
            "snapshot at %s: state file %s unreadable (%s); quarantining",
            directory, state_name, e,
        )
        return "integrity"
    template = store.agg.state
    if len(leaves) != len(template):
        logger.warning(
            "snapshot at %s has %d state leaves but this build expects "
            "%d (leaf count mismatch); ignoring",
            directory, len(leaves), len(template),
        )
        return "incompatible"
    # layout drift fails HERE with names, not later as an opaque device
    # error mid-device_put (same version+config can still disagree when
    # a leaf's derived sizing rule changed between builds)
    fields = getattr(type(template), "_fields", None)
    for i, (leaf, tmpl) in enumerate(zip(leaves, template)):
        if tuple(leaf.shape) != tuple(tmpl.shape) or leaf.dtype != tmpl.dtype:
            logger.warning(
                "snapshot at %s: leaf %s has shape %s dtype %s but the "
                "live state template expects shape %s dtype %s (state "
                "layout drift); ignoring",
                directory, fields[i] if fields else f"f{i}",
                tuple(leaf.shape), leaf.dtype,
                tuple(tmpl.shape), tmpl.dtype,
            )
            return "incompatible"
    # integrity manifest: recompute each leaf's digest against the
    # meta's record. Legacy metas (no manifest) restore unchecked —
    # the un-generationed layout predates the digests.
    crcs = meta.get("leaf_crcs")
    if crcs is not None:
        if len(crcs) != len(leaves):
            logger.warning(
                "snapshot at %s: digest manifest has %d entries for %d "
                "leaves; quarantining", directory, len(crcs), len(leaves),
            )
            return "integrity"
        got = leaf_digests(leaves)
        for i, (want_crc, got_crc) in enumerate(zip(crcs, got)):
            if int(want_crc) != got_crc:
                logger.warning(
                    "snapshot at %s: leaf %s digest mismatch (crc32 "
                    "%08x != manifest %08x) — bit rot in %s; quarantining",
                    directory, fields[i] if fields else f"f{i}",
                    got_crc, int(want_crc), state_name,
                )
                return "integrity"
    with store.agg.lock:
        store.agg.state = jax.device_put(
            type(template)(*leaves), store.agg._sharding
        )
        store.agg.sync_pend_lanes()

    saved_counters = meta.get("counters", {})
    for key in store.agg.host_counters:
        if key in saved_counters:
            store.agg.host_counters[key] = int(saved_counters[key])

    # vocab restore (ids must keep their meaning across restarts)
    store.vocab.services._names = list(meta["services"])
    store.vocab.services._ids = {n: i for i, n in enumerate(meta["services"]) if i}
    store.vocab.span_names._names = list(meta["span_names"])
    store.vocab.span_names._ids = {
        n: i for i, n in enumerate(meta["span_names"]) if i
    }
    store.vocab._key_list = [tuple(k) for k in meta["keys"]]
    store.vocab._keys = {tuple(k): i for i, k in enumerate(meta["keys"]) if i}
    store.agg.wal_seq = int(meta.get("wal_seq", 0))
    # host mirrors that shadow restored leaves (the sampling tier seeds
    # its published tables from shard 0's copy — leaves are replicated)
    on_leaves = getattr(store, "on_restored_leaves", None)
    if on_leaves is not None:
        on_leaves(dict(zip(fields or (), leaves)))
    logger.info("restored TPU sketch snapshot from %s", directory)
    return "ok"


def retained_coverage(directory: str) -> Optional[int]:
    """The wal_seq floor the WAL must keep replayable: the MINIMUM
    wal_seq across every retained (non-quarantined) generation. With
    K-generation retention, truncating at the newest generation's
    wal_seq would delete exactly the suffix a fallback restore needs —
    the oldest retained generation is the coverage rule (ISSUE 7).
    None when nothing restorable exists."""
    seqs = []
    meta_path = os.path.join(directory, META_FILE)
    try:
        with open(meta_path) as f:
            seqs.append(int(json.load(f).get("wal_seq", 0)))
    except (OSError, ValueError):
        pass
    for _, name in _state_generations(directory):
        try:
            with open(os.path.join(directory, _gen_meta_name(name))) as f:
                seqs.append(int(json.load(f).get("wal_seq", 0)))
        except (OSError, ValueError):
            continue
    return min(seqs) if seqs else None


def generation_status(directory: str) -> List[dict]:
    """Durability inventory for the statusz debug plane: every
    generation on disk (quarantined included), newest first."""
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return out
    for name in names:
        stem, quarantined = name, False
        if stem.endswith(QUARANTINE_SUFFIX):
            stem, quarantined = stem[: -len(QUARANTINE_SUFFIX)], True
        if not (stem.startswith(_STATE_PREFIX) and stem.endswith(".npz")):
            continue
        try:
            gen = int(stem[len(_STATE_PREFIX):-4])
        except ValueError:
            continue
        entry = {
            "generation": gen,
            "stateFile": name,
            "quarantined": quarantined,
            "walSeq": None,
            "bytes": 0,
        }
        try:
            entry["bytes"] = os.path.getsize(os.path.join(directory, name))
        except OSError:
            pass
        for gm in (
            _gen_meta_name(stem),
            _gen_meta_name(stem) + QUARANTINE_SUFFIX,
        ):
            try:
                with open(os.path.join(directory, gm)) as f:
                    entry["walSeq"] = int(json.load(f).get("wal_seq", 0))
                break
            except (OSError, ValueError):
                continue
        out.append(entry)
    out.sort(key=lambda e: -e["generation"])
    return out
