"""Checkpoint/resume of device sketch state.

The reference has no in-process durability — it delegates to storage
backends and replays from Kafka offsets (SURVEY.md §5 checkpoint row).
The TPU tier's aggregates live in volatile HBM, so durability is
explicit here: pull the sharded state to host, write one ``.npz`` plus
the string vocabularies as JSON, restore on boot. Snapshots are atomic
(write to temp, rename) and self-describing (config + shard count are
validated on restore).

Replay markers: the snapshot records ingest counters; transports that
support offsets (replay files, Kafka) can resume from
``counters["spans"]`` — the analog of Kafka consumer-offset resume.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import time
from typing import TYPE_CHECKING, Optional

import numpy as np

if TYPE_CHECKING:  # pragma: no cover
    from zipkin_tpu.tpu.store import TpuStorage

logger = logging.getLogger(__name__)

STATE_FILE = "sketch_state.npz"
META_FILE = "meta.json"

# Bump whenever the AggState pytree or the config serialization changes
# shape (ADVICE r2: v1 silently covered two incompatible layouts and
# restore failures misattributed the cause to operator config changes).
# v2 = r2 retention layout (hist_t/rollup leaves, retention config keys).
SNAPSHOT_VERSION = 2


def save(store: "TpuStorage", directory: str) -> str:
    """Snapshot sketches + vocab into ``directory`` (atomic). Returns path."""
    os.makedirs(directory, exist_ok=True)
    # consistency: the state is CLONED on device under the aggregator
    # lock together with wal_seq AND the host counters (so "state +
    # counters + everything after wal_seq" describe the same instant),
    # then pulled to host lock-free — holding the lock through the pull
    # would stall ingest for the whole transfer (concurrent steps donate
    # the live buffers, but the clone's are independent).
    clone, wal_seq, counters = store.agg.state_clone()
    arrays = {f"f{i}": np.asarray(leaf) for i, leaf in enumerate(clone)}

    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:  # file object: savez won't append ".npz"
        np.savez_compressed(f, **arrays)
    os.replace(tmp, os.path.join(directory, STATE_FILE))

    meta = {
        "version": SNAPSHOT_VERSION,
        "saved_at": time.time(),
        "wal_seq": wal_seq,
        "n_shards": store.agg.n_shards,
        "config": dataclasses.asdict(store.config),
        # agg counters from the locked capture; vocab-overflow counters
        # are monotonic, not restored by maybe_restore, and harmless to
        # read late — so the lock-free merge is safe
        "counters": {**store.ingest_counters(), **counters},
        "services": store.vocab.services._names,
        "span_names": store.vocab.span_names._names,
        "keys": store.vocab._key_list,
    }
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(meta, f)
    os.replace(tmp, os.path.join(directory, META_FILE))
    return directory


def maybe_restore(store: "TpuStorage", directory: str) -> bool:
    """Restore state + vocab if a compatible snapshot exists."""
    state_path = os.path.join(directory, STATE_FILE)
    meta_path = os.path.join(directory, META_FILE)
    if not (os.path.exists(state_path) and os.path.exists(meta_path)):
        return False
    with open(meta_path) as f:
        meta = json.load(f)
    if meta.get("version") != SNAPSHOT_VERSION:
        logger.warning(
            "snapshot at %s has format version %s (this build writes %s); "
            "ignoring — re-snapshot after the next ingest",
            directory, meta.get("version"), SNAPSHOT_VERSION,
        )
        return False
    want = dataclasses.asdict(store.config)
    if meta.get("config") != want:
        logger.warning(
            "snapshot at %s was taken under a different AggConfig "
            "(operator config changed); ignoring", directory,
        )
        return False
    if meta.get("n_shards") != store.agg.n_shards:
        logger.warning(
            "snapshot at %s has %s shards but this mesh has %s; ignoring",
            directory, meta.get("n_shards"), store.agg.n_shards,
        )
        return False

    import jax

    loaded = np.load(state_path)
    leaves = [loaded[f"f{i}"] for i in range(len(loaded.files))]
    template = store.agg.state
    if len(leaves) != len(template):
        logger.warning("snapshot leaf count mismatch; ignoring")
        return False
    with store.agg.lock:
        store.agg.state = jax.device_put(
            type(template)(*leaves), store.agg._sharding
        )
        store.agg.sync_pend_lanes()

    saved_counters = meta.get("counters", {})
    for key in store.agg.host_counters:
        if key in saved_counters:
            store.agg.host_counters[key] = int(saved_counters[key])

    # vocab restore (ids must keep their meaning across restarts)
    store.vocab.services._names = list(meta["services"])
    store.vocab.services._ids = {n: i for i, n in enumerate(meta["services"]) if i}
    store.vocab.span_names._names = list(meta["span_names"])
    store.vocab.span_names._ids = {
        n: i for i, n in enumerate(meta["span_names"]) if i
    }
    store.vocab._key_list = [tuple(k) for k in meta["keys"]]
    store.vocab._keys = {tuple(k): i for i, k in enumerate(meta["keys"]) if i}
    store.agg.wal_seq = int(meta.get("wal_seq", 0))
    logger.info("restored TPU sketch snapshot from %s", directory)
    return True
