"""Checkpoint/resume of device sketch state.

The reference has no in-process durability — it delegates to storage
backends and replays from Kafka offsets (SURVEY.md §5 checkpoint row).
The TPU tier's aggregates live in volatile HBM, so durability is
explicit here: pull the sharded state to host, write one ``.npz`` plus
the string vocabularies as JSON, restore on boot.

Crash consistency (ISSUE 3): a snapshot is TWO files, and a crash
between their renames must never pair a new state with an old meta
(the old meta's wal_seq would double-replay batches the new state
already holds). The commit protocol makes ``meta.json`` the single
atomic commit point:

1. the state is written to a fresh generation-named file
   (``sketch_state-<gen>.npz``), fsynced, renamed in, dir fsynced —
   the previous generation is untouched;
2. ``meta.json`` (which names its state file) is written the same way —
   ``os.replace`` flips the snapshot from old pair to new pair in one
   atomic step;
3. only then are superseded state generations pruned.

A crash at any instant (the ``snapshot.post_state`` / ``post_meta``
crashpoints in zipkin_tpu.faults pin the two worst ones) leaves
meta.json referencing one COMPLETE state file. fsync before each
rename is what makes the rename itself crash-durable: a rename of
unflushed data can survive a power cut while the bytes do not.

Replay markers: the snapshot records ingest counters; transports that
support offsets (replay files, Kafka) can resume from
``counters["spans"]`` — the analog of Kafka consumer-offset resume.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import time
from typing import TYPE_CHECKING, Optional

import numpy as np

from zipkin_tpu import faults

if TYPE_CHECKING:  # pragma: no cover
    from zipkin_tpu.tpu.store import TpuStorage

logger = logging.getLogger(__name__)

STATE_FILE = "sketch_state.npz"  # legacy single-generation name (read-only)
META_FILE = "meta.json"
_STATE_PREFIX = "sketch_state-"

# Bump whenever the AggState pytree or the config serialization changes
# shape (ADVICE r2: v1 silently covered two incompatible layouts and
# restore failures misattributed the cause to operator config changes).
# v2 = r2 retention layout (hist_t/rollup leaves, retention config keys).
# v3 = sampling tier (s_rate/s_tail/s_link tables, r_keep ring column,
#      sampling/sample_rare_min config keys).
# v4 = persistent incremental link ctx (ctx_* leaves: sorted union
#      order/keys/runs/safe-candidates + resolved tree + watermark
#      cursor) — resumed ctx must be bit-identical, so it rides the
#      snapshot like every other leaf.
SNAPSHOT_VERSION = 4


def _fsync_dir(directory: str) -> None:
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def _state_generations(directory: str):
    """[(gen, filename)] for every generation-named state file, sorted."""
    out = []
    for name in os.listdir(directory):
        if name.startswith(_STATE_PREFIX) and name.endswith(".npz"):
            try:
                out.append((int(name[len(_STATE_PREFIX):-4]), name))
            except ValueError:
                continue
    out.sort()
    return out


def save(store: "TpuStorage", directory: str) -> str:
    """Snapshot sketches + vocab into ``directory`` (atomic). Returns path."""
    os.makedirs(directory, exist_ok=True)
    # consistency: the state is CLONED on device under the aggregator
    # lock together with wal_seq AND the host counters (so "state +
    # counters + everything after wal_seq" describe the same instant),
    # then pulled to host lock-free — holding the lock through the pull
    # would stall ingest for the whole transfer (concurrent steps donate
    # the live buffers, but the clone's are independent).
    clone, wal_seq, counters = store.agg.state_clone()
    arrays = {f"f{i}": np.asarray(leaf) for i, leaf in enumerate(clone)}

    # stray temp files from a crashed earlier save are dead weight
    for name in os.listdir(directory):
        if name.endswith(".tmp"):
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass

    gens = _state_generations(directory)
    gen = (gens[-1][0] + 1) if gens else 1
    state_name = f"{_STATE_PREFIX}{gen:08d}.npz"
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".npz.tmp")
    with os.fdopen(fd, "wb") as f:  # file object: savez won't append ".npz"
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, state_name))
    _fsync_dir(directory)
    faults.crashpoint("snapshot.post_state")

    meta = {
        "version": SNAPSHOT_VERSION,
        "saved_at": time.time(),
        "wal_seq": wal_seq,
        "state_file": state_name,
        "n_shards": store.agg.n_shards,
        "config": dataclasses.asdict(store.config),
        # agg counters from the locked capture; vocab-overflow counters
        # are monotonic, not restored by maybe_restore, and harmless to
        # read late — so the lock-free merge is safe
        "counters": {**store.ingest_counters(), **counters},
        "services": store.vocab.services._names,
        "span_names": store.vocab.span_names._names,
        "keys": store.vocab._key_list,
    }
    fd, tmp = tempfile.mkstemp(dir=directory, suffix=".json.tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, META_FILE))
    _fsync_dir(directory)
    faults.crashpoint("snapshot.post_meta")

    # the new pair is durable — superseded generations (and the legacy
    # un-generationed file, if this dir predates the commit protocol)
    # can go
    for old_gen, name in gens:
        if old_gen != gen:
            try:
                os.unlink(os.path.join(directory, name))
            except OSError:
                pass
    try:
        os.unlink(os.path.join(directory, STATE_FILE))
    except OSError:
        pass
    return directory


def maybe_restore(store: "TpuStorage", directory: str) -> bool:
    """Restore state + vocab if a compatible snapshot exists."""
    meta_path = os.path.join(directory, META_FILE)
    if not os.path.exists(meta_path):
        return False
    with open(meta_path) as f:
        meta = json.load(f)
    # legacy snapshots (pre-commit-protocol) have no state_file key
    state_path = os.path.join(directory, meta.get("state_file", STATE_FILE))
    if not os.path.exists(state_path):
        logger.warning(
            "snapshot at %s: meta references missing state file %s; "
            "ignoring", directory, os.path.basename(state_path),
        )
        return False
    if meta.get("version") != SNAPSHOT_VERSION:
        logger.warning(
            "snapshot at %s has format version %s (this build writes %s); "
            "ignoring — re-snapshot after the next ingest",
            directory, meta.get("version"), SNAPSHOT_VERSION,
        )
        return False
    want = dataclasses.asdict(store.config)
    if meta.get("config") != want:
        logger.warning(
            "snapshot at %s was taken under a different AggConfig "
            "(operator config changed); ignoring", directory,
        )
        return False
    if meta.get("n_shards") != store.agg.n_shards:
        logger.warning(
            "snapshot at %s has %s shards but this mesh has %s; ignoring",
            directory, meta.get("n_shards"), store.agg.n_shards,
        )
        return False

    import jax

    loaded = np.load(state_path)
    leaves = [loaded[f"f{i}"] for i in range(len(loaded.files))]
    template = store.agg.state
    if len(leaves) != len(template):
        logger.warning(
            "snapshot at %s has %d state leaves but this build expects "
            "%d (leaf count mismatch); ignoring",
            directory, len(leaves), len(template),
        )
        return False
    # layout drift fails HERE with names, not later as an opaque device
    # error mid-device_put (same version+config can still disagree when
    # a leaf's derived sizing rule changed between builds)
    fields = getattr(type(template), "_fields", None)
    for i, (leaf, tmpl) in enumerate(zip(leaves, template)):
        if tuple(leaf.shape) != tuple(tmpl.shape) or leaf.dtype != tmpl.dtype:
            logger.warning(
                "snapshot at %s: leaf %s has shape %s dtype %s but the "
                "live state template expects shape %s dtype %s (state "
                "layout drift); ignoring",
                directory, fields[i] if fields else f"f{i}",
                tuple(leaf.shape), leaf.dtype,
                tuple(tmpl.shape), tmpl.dtype,
            )
            return False
    with store.agg.lock:
        store.agg.state = jax.device_put(
            type(template)(*leaves), store.agg._sharding
        )
        store.agg.sync_pend_lanes()

    saved_counters = meta.get("counters", {})
    for key in store.agg.host_counters:
        if key in saved_counters:
            store.agg.host_counters[key] = int(saved_counters[key])

    # vocab restore (ids must keep their meaning across restarts)
    store.vocab.services._names = list(meta["services"])
    store.vocab.services._ids = {n: i for i, n in enumerate(meta["services"]) if i}
    store.vocab.span_names._names = list(meta["span_names"])
    store.vocab.span_names._ids = {
        n: i for i, n in enumerate(meta["span_names"]) if i
    }
    store.vocab._key_list = [tuple(k) for k in meta["keys"]]
    store.vocab._keys = {tuple(k): i for i, k in enumerate(meta["keys"]) if i}
    store.agg.wal_seq = int(meta.get("wal_seq", 0))
    # host mirrors that shadow restored leaves (the sampling tier seeds
    # its published tables from shard 0's copy — leaves are replicated)
    on_leaves = getattr(store, "on_restored_leaves", None)
    if on_leaves is not None:
        on_leaves(dict(zip(fields or (), leaves)))
    logger.info("restored TPU sketch snapshot from %s", directory)
    return True
