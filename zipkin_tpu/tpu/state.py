"""Device-resident aggregate state: the TPU replacement for storage rows.

Where the reference materializes every span as rows + index tables
(cassandra ``span`` / ``trace_by_service_span``, ES daily indices —
SURVEY.md §2.3), the TPU tier keeps **fixed-shape aggregate state in HBM**
(SURVEY.md §7 design stance):

- ``hll``      — [services+1, m] u8: distinct-trace registers, row per
                 service, last row global.
- ``hist``     — [keys, BUCKETS] u32: per-(service, spanName) latency
                 histograms (psum-mergeable), all-time.
- ``hist_t``   — [T, keys, BUCKETS] u32: time-sliced histograms (slice =
                 epoch-hour % T) so percentile queries can be WINDOWED —
                 the sketch analog of the reference's daily ES indices.
- ``digest``   — [keys, C, 2] f32: per-key t-digests for tight tails.
- ring columns — a circular columnar span window (capacity R) feeding the
                 windowed dependency-link job.
- rollup       — [D, S, S] per-time-bucket dependency-link matrices: when
                 ring spans are about to be overwritten, a rollup program
                 links them and folds the edges into the bucket of the
                 child span's timestamp. This is the exact analog of the
                 reference's PRE-AGGREGATED daily ``dependency`` rows
                 (cassandra schema / zipkin-dependencies job, SURVEY.md
                 §2.3, §3.5) — links survive ring eviction, and
                 ``get_dependencies(endTs, lookback)`` merges live-ring
                 links with the buckets in the window.
- ``counters`` — ingest telemetry (CollectorMetrics taxonomy, §2.2).

The whole state is one NamedTuple pytree of arrays → trivially donatable,
shard-able on a leading axis, and snapshot-able (tpu/snapshot.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

from zipkin_tpu.ops import histogram

# counter slots (keep CollectorMetrics names in docs/metrics export)
CTR_SPANS, CTR_SPANS_DROPPED, CTR_WITH_DURATION, CTR_ERRORS, CTR_BATCHES = range(5)
# tail-sampling verdict tallies (zipkin_tpu/sampling): spans the device
# sampler kept / dropped for RETENTION — sketches still saw all of them
CTR_SAMPLED_KEPT = 5
CTR_SAMPLED_DROPPED = 6
NUM_COUNTERS = 8


@dataclasses.dataclass(frozen=True)
class AggConfig:
    """Static shapes of the device state; hashable so jit can close over it."""

    max_services: int = 1024
    max_keys: int = 8192
    hll_precision: int = 11
    digest_centroids: int = 64
    # t-digest pending buffer: batches append here (cheap) and the big
    # sort-based compaction runs only when it fills — the classic digest
    # buffering trade, amortizing the K*C-point sort across many batches.
    # Must be >= the largest packed batch size. 128k lanes halve the
    # per-span compaction cost vs 64k (the sort is dominated by the
    # K*C existing-centroid lanes, so a bigger buffer is nearly free).
    digest_buffer: int = 1 << 17
    ring_capacity: int = 1 << 18  # spans retained per shard for linking
    # time-bucketed retention (the daily-index / daily-dependency-table
    # analog): D rollup slots of bucket_minutes each for link matrices,
    # T slices of slice_minutes each for windowed histograms. A slot/slice
    # is recycled when a newer epoch maps onto it, so coverage is the most
    # recent D*bucket_minutes / T*slice_minutes of traffic.
    link_buckets: int = 16
    bucket_minutes: int = 60
    hist_slices: int = 8
    hist_slice_minutes: int = 60
    # tail-sampling tier (zipkin_tpu/sampling): when on, the ingest step
    # scores every span against the published sampler tables (s_rate /
    # s_tail / s_link leaves) and records the keep verdict in the r_keep
    # ring column + counter slots 5/6. Static so sampling=False compiles
    # the exact pre-sampling step. rare_min: a (svc, rsvc) edge whose
    # published link count is below this is "rare" and always kept.
    sampling: bool = False
    sample_rare_min: int = 4
    # time-disaggregated sketch tier (tpu/timetier.py): the ingest step
    # ALSO updates a current-bucket set of sketch leaves — tb_hll /
    # tb_digest / tb_calls+tb_errs over W = time_buckets ring slots of
    # time_bucket_minutes each (slot = epoch % W, recycled exactly like
    # hist_t slices). A host-side sealer reads completed buckets out as
    # compact mergeable segments; queries over [lookback, endTs] merge
    # covering segments plus the unsealed device slots. The persisted
    # query digest is deliberately SMALLER than the cumulative update
    # digest (the SF-sketch two-stage split): time_digest_centroids
    # clusters per key per bucket. time_buckets=0 disables the tier
    # (no leaves allocated, no tt programs compiled).
    time_buckets: int = 4
    time_bucket_minutes: int = 5
    time_digest_centroids: int = 32

    def __post_init__(self) -> None:
        # the packed wire image gives service ids 16 bits and sketch keys
        # 24 (zipkin_tpu.tpu.columnar.fuse_columns); a config beyond that
        # would silently alias ids on device
        from zipkin_tpu.tpu.columnar import MAX_WIRE_KEYS, MAX_WIRE_SERVICES

        if self.max_services > MAX_WIRE_SERVICES:
            raise ValueError(
                f"max_services ({self.max_services}) exceeds the packed "
                f"wire limit ({MAX_WIRE_SERVICES})"
            )
        if self.max_keys > MAX_WIRE_KEYS:
            raise ValueError(
                f"max_keys ({self.max_keys}) exceeds the packed wire "
                f"limit ({MAX_WIRE_KEYS})"
            )

    @property
    def hll_rows(self) -> int:
        return self.max_services + 1

    @property
    def global_hll_row(self) -> int:
        return self.max_services

    @property
    def timetier_enabled(self) -> bool:
        return self.time_buckets > 0

    @property
    def rollup_segment(self) -> int:
        """Ring slots linked+invalidated per rollup: half the ring. The
        host triggers a rollup before writes since the last one exceed
        this, so no valid span is ever overwritten unrolled."""
        return self.ring_capacity // 2


class AggState(NamedTuple):
    hll: jnp.ndarray  # u8 [services+1, m]
    hist: jnp.ndarray  # u32 [keys, BUCKETS] (all-time)
    hist_t: jnp.ndarray  # u32 [T, keys, BUCKETS] (time slices)
    hist_t_epoch: jnp.ndarray  # i32 [T] — absolute slice epoch held, -1 empty
    digest: jnp.ndarray  # f32 [keys, C, 2]
    pend_key: jnp.ndarray  # i32 [P] — -1 = empty lane
    pend_val: jnp.ndarray  # f32 [P]
    pend_pos: jnp.ndarray  # i32 scalar
    # ring columns, all [R]
    r_trace_h: jnp.ndarray  # u32
    r_tl0: jnp.ndarray  # u32
    r_tl1: jnp.ndarray  # u32
    r_s0: jnp.ndarray  # u32
    r_s1: jnp.ndarray  # u32
    r_p0: jnp.ndarray  # u32
    r_p1: jnp.ndarray  # u32
    r_shared: jnp.ndarray  # bool
    r_kind: jnp.ndarray  # i32
    r_svc: jnp.ndarray  # i32
    r_rsvc: jnp.ndarray  # i32
    r_err: jnp.ndarray  # bool
    r_ts_min: jnp.ndarray  # u32
    r_valid: jnp.ndarray  # bool
    # tail-sampling verdict per ring lane (meaningful iff config.sampling;
    # all-False otherwise). The ring itself retains 100% of spans — link
    # joins need whole-trace context — r_keep only RECORDS the device
    # verdict so the parity oracle can read it back.
    r_keep: jnp.ndarray  # bool
    # rolled lanes already contributed their links to the rollup matrices:
    # they no longer EMIT edges but stay JOIN-VISIBLE (a live child can
    # still resolve a rolled parent until the lane is overwritten)
    r_rolled: jnp.ndarray  # bool
    ring_pos: jnp.ndarray  # i32 scalar
    # time-bucketed link rollups (daily dependency-table analog)
    rollup_calls: jnp.ndarray  # u32 [D, S, S]
    rollup_errs: jnp.ndarray  # u32 [D, S, S]
    rollup_epoch: jnp.ndarray  # i32 [D] — absolute bucket held, -1 empty
    # time-disaggregated sketch tier (current-bucket leaves): W ring
    # slots of time_bucket_minutes each; slot = bucket_epoch % W,
    # recycled on a newer epoch exactly like hist_t slices. tb_epoch is
    # the ONE shared epoch array — a recycle wipes every tt plane for
    # the slot. tb_digest holds the compact per-key query digest
    # (time_digest_centroids clusters); pend_ep tags each pending digest
    # point with its bucket epoch so the flush can fold points into
    # their bucket slots segmented by (slot, key).
    tb_epoch: jnp.ndarray  # i32 [W] — absolute bucket epoch held, -1 empty
    tb_hll: jnp.ndarray  # u8 [W, services+1, m]
    tb_digest: jnp.ndarray  # f32 [W, keys, Cw, 2]
    tb_calls: jnp.ndarray  # u32 [W, S, S]
    tb_errs: jnp.ndarray  # u32 [W, S, S]
    pend_ep: jnp.ndarray  # i32 [P] — bucket epoch per pending point, -1 empty
    # published tail-sampling tables (zipkin_tpu/sampling). These are
    # HOST-AUTHORITATIVE: the controller computes them on host and
    # publishes by swapping the leaves under the aggregator lock; the
    # device only READS them, so every shard holds identical content and
    # verdicts are a pure function of (span, published tables) — the
    # foundation of host/device verdict parity and crash-resume replay.
    s_rate: jnp.ndarray  # u32 [S] — per-service keep rate, 65536 = keep all
    s_tail: jnp.ndarray  # u32 [K] — per-key tail-latency threshold (µs)
    s_link: jnp.ndarray  # u32 [S, S] — published (svc, rsvc) edge counts
    # persistent incremental link context (ops/delta_linker.py): the
    # sorted join-union order over the ring, its run decomposition, the
    # per-run first-wins candidates restricted to lanes that cannot be
    # overwritten before the next advance, and the resolved tree at the
    # last advance. Advanced at rollup cadence; a fresh dependency read
    # pays only the since-advance delta segment against these.
    ctx_order: jnp.ndarray  # i32 [2R] union index per sorted position
    ctx_keys: jnp.ndarray  # u32 [4, 2R] sort-key snapshot per position
    ctx_rid_c: jnp.ndarray  # i32 [2R] coarse run id (1-based)
    ctx_rid_f: jnp.ndarray  # i32 [2R] fine run id (1-based)
    ctx_inv: jnp.ndarray  # i32 [2R] sorted position of union entry u
    ctx_safe_sh: jnp.ndarray  # i32 [2R] first safe shared lane per run
    ctx_safe_ns: jnp.ndarray  # i32 [2R] first safe non-shared lane per run
    ctx_safe_fsh: jnp.ndarray  # i32 [2R] first safe shared lane, fine run
    ctx_parent: jnp.ndarray  # i32 [R] resolved parent lane at the advance
    ctx_anc: jnp.ndarray  # i32 [R] nearest-RPC-ancestor lane at the advance
    ctx_root: jnp.ndarray  # bool [R] parent chain reaches a root
    ctx_pos: jnp.ndarray  # i32 scalar — covered-watermark lane cursor
    ctx_delta: jnp.ndarray  # i32 scalar — lanes written since the advance
    counters: jnp.ndarray  # u32 [NUM_COUNTERS]


def init_state(config: AggConfig) -> AggState:
    r = config.ring_capacity
    z32 = jnp.zeros((r,), jnp.uint32)
    return AggState(
        hll=jnp.zeros((config.hll_rows, 1 << config.hll_precision), jnp.uint8),
        hist=jnp.zeros((config.max_keys, histogram.BUCKETS), jnp.uint32),
        hist_t=jnp.zeros(
            (config.hist_slices, config.max_keys, histogram.BUCKETS), jnp.uint32
        ),
        hist_t_epoch=jnp.full((config.hist_slices,), -1, jnp.int32),
        digest=jnp.zeros((config.max_keys, config.digest_centroids, 2), jnp.float32),
        pend_key=jnp.full((config.digest_buffer,), -1, jnp.int32),
        pend_val=jnp.zeros((config.digest_buffer,), jnp.float32),
        pend_pos=jnp.zeros((), jnp.int32),
        r_trace_h=z32, r_tl0=z32, r_tl1=z32, r_s0=z32, r_s1=z32,
        r_p0=z32, r_p1=z32,
        r_shared=jnp.zeros((r,), bool),
        r_kind=jnp.zeros((r,), jnp.int32),
        r_svc=jnp.zeros((r,), jnp.int32),
        r_rsvc=jnp.zeros((r,), jnp.int32),
        r_err=jnp.zeros((r,), bool),
        r_ts_min=z32,
        r_valid=jnp.zeros((r,), bool),
        r_keep=jnp.zeros((r,), bool),
        r_rolled=jnp.zeros((r,), bool),
        ring_pos=jnp.zeros((), jnp.int32),
        rollup_calls=jnp.zeros(
            (config.link_buckets, config.max_services, config.max_services),
            jnp.uint32,
        ),
        rollup_errs=jnp.zeros(
            (config.link_buckets, config.max_services, config.max_services),
            jnp.uint32,
        ),
        rollup_epoch=jnp.full((config.link_buckets,), -1, jnp.int32),
        tb_epoch=jnp.full((config.time_buckets,), -1, jnp.int32),
        tb_hll=jnp.zeros(
            (config.time_buckets, config.hll_rows, 1 << config.hll_precision),
            jnp.uint8,
        ),
        tb_digest=jnp.zeros(
            (
                config.time_buckets,
                config.max_keys,
                config.time_digest_centroids,
                2,
            ),
            jnp.float32,
        ),
        tb_calls=jnp.zeros(
            (config.time_buckets, config.max_services, config.max_services),
            jnp.uint32,
        ),
        tb_errs=jnp.zeros(
            (config.time_buckets, config.max_services, config.max_services),
            jnp.uint32,
        ),
        pend_ep=jnp.full(
            (config.digest_buffer if config.time_buckets else 0,),
            -1,
            jnp.int32,
        ),
        # sampler tables boot in "keep everything" posture: max rate, an
        # unreachable tail threshold, and zero published link counts
        # (every edge rare). The controller publishes real tables later.
        s_rate=jnp.full((config.max_services,), 65536, jnp.uint32),
        s_tail=jnp.full((config.max_keys,), 0xFFFFFFFF, jnp.uint32),
        s_link=jnp.zeros(
            (config.max_services, config.max_services), jnp.uint32
        ),
        # incremental link ctx of the all-invalid ring (every union key
        # 0xFFFFFFFF -> identity order is validly sorted, one run, no
        # candidates) — exactly what an advance over the empty ring
        # yields, so the first real advance is indistinguishable from
        # one that followed an earlier empty advance
        ctx_order=jnp.arange(2 * r, dtype=jnp.int32),
        ctx_keys=jnp.full((4, 2 * r), 0xFFFFFFFF, jnp.uint32),
        ctx_rid_c=jnp.ones((2 * r,), jnp.int32),
        ctx_rid_f=jnp.ones((2 * r,), jnp.int32),
        ctx_inv=jnp.arange(2 * r, dtype=jnp.int32),
        ctx_safe_sh=jnp.full((2 * r,), -1, jnp.int32),
        ctx_safe_ns=jnp.full((2 * r,), -1, jnp.int32),
        ctx_safe_fsh=jnp.full((2 * r,), -1, jnp.int32),
        ctx_parent=jnp.full((r,), -1, jnp.int32),
        ctx_anc=jnp.full((r,), -1, jnp.int32),
        ctx_root=jnp.ones((r,), bool),
        ctx_pos=jnp.zeros((), jnp.int32),
        ctx_delta=jnp.zeros((), jnp.int32),
        counters=jnp.zeros((NUM_COUNTERS,), jnp.uint32),
    )


def state_bytes(config: AggConfig) -> int:
    """HBM footprint of one shard's state (for capacity planning)."""
    import numpy as np

    s = init_state(config)
    return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in s))
