"""Device-resident aggregate state: the TPU replacement for storage rows.

Where the reference materializes every span as rows + index tables
(cassandra ``span`` / ``trace_by_service_span``, ES daily indices —
SURVEY.md §2.3), the TPU tier keeps **fixed-shape aggregate state in HBM**
(SURVEY.md §7 design stance):

- ``hll``      — [services+1, m] u8: distinct-trace registers, row per
                 service, last row global.
- ``hist``     — [keys, BUCKETS] u32: per-(service, spanName) latency
                 histograms (psum-mergeable).
- ``digest``   — [keys, C, 2] f32: per-key t-digests for tight tails.
- ring columns — a circular columnar span window (capacity R) feeding the
                 windowed dependency-link job; the HBM analog of the
                 reference's time-bucketed retention (daily ES indices).
- ``counters`` — ingest telemetry (CollectorMetrics taxonomy, §2.2).

The whole state is one NamedTuple pytree of arrays → trivially donatable,
shard-able on a leading axis, and snapshot-able (tpu/snapshot.py).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import jax.numpy as jnp

from zipkin_tpu.ops import histogram

# counter slots (keep CollectorMetrics names in docs/metrics export)
CTR_SPANS, CTR_SPANS_DROPPED, CTR_WITH_DURATION, CTR_ERRORS, CTR_BATCHES = range(5)
NUM_COUNTERS = 8


@dataclasses.dataclass(frozen=True)
class AggConfig:
    """Static shapes of the device state; hashable so jit can close over it."""

    max_services: int = 1024
    max_keys: int = 8192
    hll_precision: int = 11
    digest_centroids: int = 64
    # t-digest pending buffer: batches append here (cheap) and the big
    # sort-based compaction runs only when it fills — the classic digest
    # buffering trade, amortizing the K*C-point sort across many batches.
    # Must be >= the largest packed batch size. 128k lanes halve the
    # per-span compaction cost vs 64k (the sort is dominated by the
    # K*C existing-centroid lanes, so a bigger buffer is nearly free).
    digest_buffer: int = 1 << 17
    ring_capacity: int = 1 << 17  # spans retained per shard for linking

    @property
    def hll_rows(self) -> int:
        return self.max_services + 1

    @property
    def global_hll_row(self) -> int:
        return self.max_services


class AggState(NamedTuple):
    hll: jnp.ndarray  # u8 [services+1, m]
    hist: jnp.ndarray  # u32 [keys, BUCKETS]
    digest: jnp.ndarray  # f32 [keys, C, 2]
    pend_key: jnp.ndarray  # i32 [P] — -1 = empty lane
    pend_val: jnp.ndarray  # f32 [P]
    pend_pos: jnp.ndarray  # i32 scalar
    # ring columns, all [R]
    r_trace_h: jnp.ndarray  # u32
    r_tl0: jnp.ndarray  # u32
    r_tl1: jnp.ndarray  # u32
    r_s0: jnp.ndarray  # u32
    r_s1: jnp.ndarray  # u32
    r_p0: jnp.ndarray  # u32
    r_p1: jnp.ndarray  # u32
    r_shared: jnp.ndarray  # bool
    r_kind: jnp.ndarray  # i32
    r_svc: jnp.ndarray  # i32
    r_rsvc: jnp.ndarray  # i32
    r_err: jnp.ndarray  # bool
    r_ts_min: jnp.ndarray  # u32
    r_valid: jnp.ndarray  # bool
    ring_pos: jnp.ndarray  # i32 scalar
    counters: jnp.ndarray  # u32 [NUM_COUNTERS]


def init_state(config: AggConfig) -> AggState:
    r = config.ring_capacity
    z32 = jnp.zeros((r,), jnp.uint32)
    return AggState(
        hll=jnp.zeros((config.hll_rows, 1 << config.hll_precision), jnp.uint8),
        hist=jnp.zeros((config.max_keys, histogram.BUCKETS), jnp.uint32),
        digest=jnp.zeros((config.max_keys, config.digest_centroids, 2), jnp.float32),
        pend_key=jnp.full((config.digest_buffer,), -1, jnp.int32),
        pend_val=jnp.zeros((config.digest_buffer,), jnp.float32),
        pend_pos=jnp.zeros((), jnp.int32),
        r_trace_h=z32, r_tl0=z32, r_tl1=z32, r_s0=z32, r_s1=z32,
        r_p0=z32, r_p1=z32,
        r_shared=jnp.zeros((r,), bool),
        r_kind=jnp.zeros((r,), jnp.int32),
        r_svc=jnp.zeros((r,), jnp.int32),
        r_rsvc=jnp.zeros((r,), jnp.int32),
        r_err=jnp.zeros((r,), bool),
        r_ts_min=z32,
        r_valid=jnp.zeros((r,), bool),
        ring_pos=jnp.zeros((), jnp.int32),
        counters=jnp.zeros((NUM_COUNTERS,), jnp.uint32),
    )


def state_bytes(config: AggConfig) -> int:
    """HBM footprint of one shard's state (for capacity planning)."""
    import numpy as np

    s = init_state(config)
    return int(sum(np.prod(a.shape) * a.dtype.itemsize for a in s))
