"""TpuStorage: the StorageComponent backed by the device aggregation tier.

This is the rebuild's ``zipkin-storage-tpu`` module (BASELINE north
star): it implements the exact SPI of SURVEY.md §2.3 — so the collectors
and server use it interchangeably with the in-memory oracle — while
serving the aggregate read paths (dependencies, latency percentiles,
cardinalities) straight from device sketches.

Division of labor (hybrid by design, SURVEY.md §1 "TPU-rebuild mapping"):

- **Device** (per shard, merged over ICI on read): latency histograms +
  t-digests per (service, spanName), HLL trace cardinality per service,
  dependency-link matrices over the retained span ring.
- **Host archive**: a bounded `InMemoryStorage` keeps raw spans for exact
  trace reads and search (`getTraces`) — the role the reference delegates
  to row storage; beyond its eviction horizon, aggregates remain
  queryable from the device (which is the point of the sketch tier).

Idempotence: at-least-once transports can redeliver (SURVEY.md §3.3). The
archive dedups by (traceId, spanId, ...); device sketches accept bounded
double-count — the documented trade, testable against the oracle.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu import obs, readpack
from zipkin_tpu.internal.hex import epoch_minutes
from zipkin_tpu.obs import querytrace
from zipkin_tpu.ops import hll, ttmerge
from zipkin_tpu.model.span import DependencyLink, Span
from zipkin_tpu.storage.memory import InMemoryStorage
from zipkin_tpu.storage.spi import (
    AutocompleteTags,
    QueryRequest,
    ServiceAndSpanNames,
    SpanConsumer,
    SpanStore,
    StorageComponent,
)
from zipkin_tpu.tpu.columnar import SpanColumns, Vocab, pack_spans
from zipkin_tpu.tpu.mirror import ReadMirror
from zipkin_tpu.tpu.state import AggConfig
from zipkin_tpu.utils.call import Call
from zipkin_tpu.utils.component import CheckResult, Component

logger = logging.getLogger(__name__)

# the dashboard's default quantile list (server endpoints default
# ``q=0.5,0.9,0.99``): the mirror seeds these reads at construction so
# the first post-boot dashboard refresh is already lock-free
DEFAULT_QS = (0.5, 0.9, 0.99)

# sentinel returned by _mirror_bound when the request opted out of the
# mirror (staleness_ms <= 0): force the fresh lock-path read
_MIRROR_FRESH = object()


from zipkin_tpu.native import PARSED_FIELDS as _PARSED_FIELDS


def _decode_raw_span(raw: bytes):
    """Decode one archived raw span slice: JSON objects start '{', a
    proto3 Span message starts with a field tag byte — the archive holds
    whichever wire format ingested the span."""
    if raw[:1] == b"{":
        from zipkin_tpu.model import json_v2

        return json_v2.decode_one_span(raw)
    from zipkin_tpu.model import proto3

    return proto3.decode_span(raw)


class TpuStorage(
    StorageComponent, SpanConsumer, SpanStore, ServiceAndSpanNames, AutocompleteTags
):
    def __init__(
        self,
        *,
        config: Optional[AggConfig] = None,
        mesh=None,
        strict_trace_id: bool = True,
        search_enabled: bool = True,
        autocomplete_keys: Sequence[str] = (),
        archive_max_span_count: int = 500_000,
        pad_to_multiple: int = 1024,
        fast_archive_sample: int = 64,
        archive_dir: Optional[str] = None,
        archive_max_bytes: int = 2 << 30,
        archive_segment_bytes: int = 64 << 20,
        sampling_budget: float = 0.0,
        sampling_interval_s: float = 5.0,
        sampling_min_rate: int = 256,
        sampling_tail_quantile: float = 0.99,
        sampling_rare_min: Optional[int] = None,
    ) -> None:
        from zipkin_tpu.parallel.sharded import ShardedAggregator

        self.config = config or AggConfig()
        # NOTE: the archive index packs svc/rsvc ids into 16 bits each
        # (tpu/archive.py COLS row 6). AggConfig already rejects
        # max_services beyond the packed-wire 16-bit limit (state.py /
        # columnar.MAX_WIRE_SERVICES), so a truncating capacity is
        # unconstructable — pinned by
        # tests/test_disk_archive.py::test_service_capacity_guard.
        self.strict_trace_id = strict_trace_id
        self.search_enabled = search_enabled
        self.autocomplete_keys = tuple(autocomplete_keys)
        self.vocab = Vocab(
            max_services=self.config.max_services, max_keys=self.config.max_keys
        )
        self.agg = ShardedAggregator(self.config, mesh=mesh)
        # adaptive tail-sampling tier (zipkin_tpu/sampling): the host
        # reference sampler gates RETENTION (WAL via ingest_fused, disk
        # archive, RAM archive sample) while device sketches keep seeing
        # 100% of spans. Installed on the aggregator immediately for a
        # cold boot; the resume adapter (storage/tpu.py) detaches it
        # around restore/replay and re-installs after the final tables
        # are pushed back to the device.
        self.sampler = None
        self.sampling_controller = None
        if self.config.sampling:
            from zipkin_tpu.sampling import HostSampler, RateController

            self.sampler = HostSampler(
                self.config.max_services,
                self.config.max_keys,
                rare_min=(
                    self.config.sample_rare_min
                    if sampling_rare_min is None
                    else sampling_rare_min
                ),
            )
            self.agg.sampler = self.sampler
            if sampling_budget > 0:
                self.sampling_controller = RateController(
                    self,
                    budget_spans_per_sec=sampling_budget,
                    interval_s=sampling_interval_s,
                    min_rate=sampling_min_rate,
                    tail_quantile=sampling_tail_quantile,
                )
        self._archive = InMemoryStorage(
            max_span_count=archive_max_span_count,
            strict_trace_id=strict_trace_id,
            search_enabled=search_enabled,
            autocomplete_keys=autocomplete_keys,
        )
        self._pad = pad_to_multiple
        # largest single device batch AFTER padding: bounded by the digest
        # pending buffer (dynamic_update_slice of a batch bigger than it
        # cannot trace), rounded DOWN to a pad multiple so a padded chunk
        # never exceeds the bound.
        # Dispatch on the tunneled PJRT backend carries a large fixed
        # latency, so bigger device batches amortize it — but only up to
        # the relay's message size: an r3 A/B on the chip measured 64k
        # batches (2.9MB wire) at 352k spans/s vs 128k batches (5.8MB) at
        # 106k in the SAME clean window, so 64k stays the default and the
        # cap is an env knob for other transports. Hard bound either way:
        # the digest pending buffer (dynamic_update_slice of a batch
        # bigger than it cannot trace).
        import os as _os

        cap = int(_os.environ.get("TPU_MAX_DEVICE_BATCH", 65536))
        bound = min(self.config.digest_buffer, self.config.rollup_segment, cap)
        self.max_batch = (bound // pad_to_multiple) * pad_to_multiple
        if self.max_batch <= 0:
            raise ValueError(
                f"digest_buffer ({self.config.digest_buffer}) must be >= "
                f"pad_to_multiple ({pad_to_multiple})"
            )
        self._closed = False
        # boot-time restore instrumentation (ISSUE 3): zeros on a cold
        # boot; the resume-capable storage adapter (storage/tpu.py)
        # overwrites these with measured restore/replay figures, and
        # they flow to /prometheus + /metrics via ingest_counters()
        self.restore_stats = {
            "restoreMs": 0.0,
            "walReplayBatches": 0,
            "walReplayMs": 0.0,
            # bit-rot accounting (ISSUE 7): how many snapshot
            # generations the last boot quarantined, and whether it had
            # to fall back past the newest one (tpu/snapshot.py)
            "restoreFallbacks": 0,
            "generationsQuarantined": 0,
        }
        # background at-rest CRC scrubber (runtime/scrub.py); installed
        # by the resume-capable adapter when scrubbing is enabled, its
        # counters merge into ingest_counters below
        self.scrubber = None
        # disk-backed raw-span archive (VERDICT r3 order 2): when set,
        # EVERY ingested span's raw JSON is retained on disk behind a
        # trace-id index (retention = a disk-byte budget), so fast-mode
        # get_trace returns the COMPLETE trace for any acked id in the
        # window — not the 1-in-64 RAM sample. See tpu/archive.py.
        self._disk = None
        self._archive_vocab_path = None
        self._archive_vocab_persisted = 0
        if archive_dir:
            from zipkin_tpu.tpu.archive import SpanArchive

            self._disk = SpanArchive(
                archive_dir,
                max_bytes=archive_max_bytes,
                segment_bytes=archive_segment_bytes,
            )
            import os as _os2

            self._archive_vocab_path = _os2.path.join(
                archive_dir, "vocab.json"
            )
        # remote services per service (svc_id -> set of rsvc ids) and the
        # set of ids seen as a LOCAL service: the disk index serves
        # search, but these tiny host maps answer getServiceNames /
        # getRemoteServiceNames without a segment scan. The vocab alone
        # cannot answer either — remote names intern into the same
        # services table, and the reference lists LOCAL names only.
        self._remote_by_svc: dict = {}
        self._local_svc_ids: set = set()
        self._names_lock = threading.Lock()
        # fast-mode archive sampling: 1 in N traces keeps full raw spans
        # (0 disables). Trace-affine so sampled traces are COMPLETE.
        # Kept CONFIGURED even with the disk archive on: the sync fast
        # path then skips RAM sampling (disk holds everything), and the
        # MP tier's workers ship raw records to the disk archive too
        # (mp_ingest remaps worker-local vocab ids and appends) — their
        # RAM sample at this rate then only backs autocompleteTags, or
        # everything when no disk archive is configured.
        self._fast_archive_every = fast_archive_sample
        # optional attached MP fan-out tier (tpu/mp_ingest.py): the
        # server sets this so ingest_counters() surfaces the tier's
        # gauges and close() can tear a forgotten tier down
        self.mp_ingester = None
        # accuracy observatory (obs/shadow.py + obs/accuracy.py): the
        # server attaches both when the shadow plane is enabled; the
        # fast path offers its columnar batches to the shadow and
        # ingest_counters() merges the accuracy gauges
        self.shadow = None
        self.accuracy = None
        # interning id-space coherence: the C-side vocab (fast path) and
        # the Python vocab (object path) assign ids sequentially; any
        # operation that interns must hold this lock so the orders match.
        self._intern_lock = threading.RLock()
        # serializes vocab-sidecar persistence (snapshot + atomic
        # replace) so concurrent writers cannot reorder replaces
        self._persist_lock = threading.Lock()
        self._nvocab = None
        # HLL operating envelope (r5 billion-scale study): cardinality
        # estimates past this are bias-dominated, not noise-dominated.
        # DERIVED from the measured bias curve at this precision, never
        # hard-coded — see ops/hll.envelope_max (~1.8e9 at p=11).
        self._hll_envelope_max = hll.envelope_max(self.config.hll_precision)
        self._hll_envelope_exceeded = 0      # reads that saw such a row
        self._hll_beyond_envelope_rows = 0   # rows beyond, at last read
        # read cache: device pulls (merged digest/sketches) keyed by the
        # write version, so repeated queries between writes cost nothing
        self._read_cache: dict = {}   # key -> (value, born_monotonic)
        self._read_cache_version = -1
        self._read_cache_lock = threading.Lock()
        # cached-read staleness: age-at-serve of the last hit and its
        # high-water — "query_cached is fast" is only good news if the
        # answers are also young; these gauges put a number on it
        self._read_cache_age_ms = 0.0
        self._read_cache_age_max_ms = 0.0
        # overload control plane (runtime/overload.py, ISSUE 13): the
        # server wires its brownout controller here. Under B1/B2 the
        # cached-read path serves CACHE-FIRST — a version-stale entry
        # within the controller's staleness bound beats a device pull
        # that would queue behind a saturated ingest lock; under B3 any
        # cached answer serves (cache-only). Stale serves are counted
        # so "the queries stayed fast" can be audited against "and this
        # many answers were seconds old".
        self.overload = None
        self._read_cache_stale_serves = 0
        # dependency answers additionally tolerate BOUNDED STALENESS
        # under sustained ingest (env TPU_DEPS_MAX_STALE_MS, default 5s;
        # 0 = always fresh): the reference's dependency table is written
        # by an OFFLINE batch job and is hours stale by design (SURVEY.md
        # §3.5), so serving a seconds-old answer instead of queueing a
        # ring re-sort behind every poll is squarely within its
        # semantics. Keyed by window; pruned by age on insert.
        import os as _os

        self._deps_max_stale_ms = float(
            _os.environ.get("TPU_DEPS_MAX_STALE_MS", 5000.0)
        )
        self._deps_cache: dict = {}
        # query-plane observatory (obs/querytrace.py): per-query
        # critical-path traces folded at tick cadence, plus the
        # aggregator-lock contention ledger. lock_provider resolves
        # self.agg lazily so clear()'s wholesale aggregator swap keeps
        # the ledger pointed at the live instrumented lock.
        self.querytrace = querytrace.QueryObservatory()
        self.querytrace.lock_provider = (
            lambda: getattr(self.agg, "lock", None)
        )
        self._query_obs_enabled: Optional[bool] = None
        # epoch-published read mirror (tpu/mirror.py, ISSUE 14): the
        # publisher — windows ticker in production, boot publish in the
        # resume adapter — takes the aggregator lock ONCE per epoch and
        # republishes every demanded read; queries then serve lock-free
        # with a stamped staleness age. The provider resolves self.agg
        # lazily for the same reason the querytrace lock provider does.
        self.mirror = ReadMirror(lambda: getattr(self, "agg", None))
        self._seed_mirror()
        # scale-out read serving (serving/, ISSUE 19): when a shm
        # mirror segment is attached, every mirror epoch additionally
        # serializes into it (outside the aggregator lock) and reader
        # PROCESSES serve from the mapped copy; their missed keys come
        # back through the segment's demand stripes each tick.
        self._segment = None
        self._segment_publisher = None
        self._demand_unparsed = 0
        # time-disaggregated sketch tier (tpu/timetier.py, ISSUE 15):
        # a ticker-driven sealer freezes finished device time buckets
        # into host-side mergeable segments; windowed [lookback, endTs]
        # quantile/cardinality/dependency reads then merge the covering
        # segments in numpy, with at most one device pull for the
        # unsealed current bucket. Segments persist under the archive
        # dir (when configured) so old windows survive restarts.
        self.timetier = None
        if self.config.timetier_enabled:
            from zipkin_tpu.tpu.timetier import TimeTier

            self.timetier = TimeTier(
                self.config,
                directory=(
                    _os.path.join(archive_dir, "timetier")
                    if archive_dir else None
                ),
            )
        # archive-only restart: segment columns store vocab IDS, so the
        # ids must survive the process or every recovered segment becomes
        # unsearchable. A snapshot restore (storage/tpu.py) replaces the
        # vocab wholesale afterwards — its id stream is the same stream,
        # so both sources agree on every id they share; WAL replay then
        # re-adds any post-snapshot tail (r4 review finding).
        self._load_archive_vocab()

    # zt-lint: disable=ZT04 — runs once from __init__, before any other
    # thread holds a reference to the store; _persist_archive_vocab's
    # lock protects later concurrent writers, not construction
    def _load_archive_vocab(self) -> None:
        if self._archive_vocab_path is None:
            return
        import json
        import os as _os

        if not _os.path.exists(self._archive_vocab_path):
            return
        if len(self.vocab.services) > 1 or self.vocab.num_keys > 1:
            return  # a live vocab wins (tests reuse dirs)
        try:
            with open(self._archive_vocab_path) as f:
                meta = json.load(f)
        except Exception:  # pragma: no cover - torn sidecar
            logger.warning("archive vocab sidecar unreadable; search over "
                           "recovered segments will miss pre-restart spans")
            return
        # digest coverage (ISSUE 7): the sidecar self-records a crc32 of
        # its canonical payload; rot here would silently remap every id
        # on recovered segments. A bad sidecar is quarantined (renamed,
        # never unlinked) and the boot degrades exactly like a missing
        # one. Pre-digest sidecars (no crc32 key) load unchecked.
        want_crc = meta.pop("crc32", None)
        if want_crc is not None:
            import zlib as _zlib

            got = _zlib.crc32(
                json.dumps(meta, sort_keys=True, separators=(",", ":")).encode()
            )
            if got != int(want_crc):
                logger.warning(
                    "archive vocab sidecar digest mismatch (crc32 %08x != "
                    "recorded %08x) — bit rot; quarantining. Search over "
                    "recovered segments will miss pre-restart spans",
                    got, int(want_crc),
                )
                try:
                    _os.replace(
                        self._archive_vocab_path,
                        self._archive_vocab_path + ".quarantine",
                    )
                except OSError:
                    pass
                return
        v = self.vocab
        v.services._names = list(meta["services"])
        v.services._ids = {
            n: i for i, n in enumerate(meta["services"]) if i
        }
        v.span_names._names = list(meta["span_names"])
        v.span_names._ids = {
            n: i for i, n in enumerate(meta["span_names"]) if i
        }
        v._key_list = [tuple(k) for k in meta["keys"]]
        v._keys = {tuple(k): i for i, k in enumerate(meta["keys"]) if i}
        with self._names_lock:
            self._local_svc_ids = set(meta.get("local_svc_ids", ()))
            self._remote_by_svc = {
                int(k): set(vv)
                for k, vv in meta.get("remote_by_svc", {}).items()
            }
        self._archive_vocab_persisted = len(v._key_list) + len(
            v.services._names
        ) + len(v.span_names._names)

    def _persist_archive_vocab(self) -> None:
        """Write the vocab sidecar when it grew since the last write
        (atomic rename; amortized to vocab growth, which is bounded).
        The whole snapshot+write+replace runs under a dedicated persist
        lock: without it a delayed writer (object path racing the sync
        fast path) could os.replace a NEWER sidecar with an older
        snapshot after `_archive_vocab_persisted` already moved past it
        — a crash in that window would leave recovered segments holding
        ids missing from the sidecar (ADVICE r4). The intern lock is
        held only for the snapshot so persistence IO never stalls
        line-rate interning."""
        if self._archive_vocab_path is None:
            return
        import json
        import os as _os
        import tempfile as _tempfile

        v = self.vocab
        # lock-free pre-check: the overwhelmingly common call sees an
        # unchanged vocab and must NOT queue behind a concurrent
        # writer's sidecar IO (every disk append calls this)
        with self._intern_lock:
            size = len(v._key_list) + len(v.services._names) + len(
                v.span_names._names
            )
            if size == self._archive_vocab_persisted:
                return
        with self._persist_lock:
            with self._intern_lock:
                size = len(v._key_list) + len(v.services._names) + len(
                    v.span_names._names
                )
                if size == self._archive_vocab_persisted:
                    return
                with self._names_lock:
                    meta = {
                        "services": list(v.services._names),
                        "span_names": list(v.span_names._names),
                        "keys": [list(k) for k in v._key_list],
                        "local_svc_ids": sorted(self._local_svc_ids),
                        "remote_by_svc": {
                            str(k): sorted(vv)
                            for k, vv in self._remote_by_svc.items()
                        },
                    }
                self._archive_vocab_persisted = size
            import zlib as _zlib

            # self-digest over the canonical payload (see
            # _load_archive_vocab's verification)
            meta["crc32"] = _zlib.crc32(
                json.dumps(meta, sort_keys=True, separators=(",", ":")).encode()
            )
            d = _os.path.dirname(self._archive_vocab_path)
            fd, tmp = _tempfile.mkstemp(dir=d, suffix=".json.tmp")
            with _os.fdopen(fd, "w") as f:
                json.dump(meta, f)
            _os.replace(tmp, self._archive_vocab_path)

    # -- sampling tier hooks ---------------------------------------------

    def on_restored_leaves(self, leaves: dict) -> None:
        """Snapshot-restore callback (tpu/snapshot.maybe_restore): seed
        the sampling tier's host mirror from the restored device leaves
        (shard 0's copy — the published tables are replicated across
        shards by construction)."""
        if self.sampler is None or "s_rate" not in leaves:
            return
        self.sampler.restore_tables(
            leaves["s_rate"][0], leaves["s_tail"][0], leaves["s_link"][0]
        )

    def apply_sctl(self, delta: dict) -> None:
        """WAL-replay callback (tpu/wal.replay): apply one replayed
        controller publish to the host mirror at its exact point of the
        batch stream, so later replayed verdicts read the same tables
        the live run did. The device leaves are pushed to match when the
        resume adapter re-installs the sampler (storage/tpu.py)."""
        if self.sampler is not None:
            self.sampler.apply_sctl(delta)

    def install_sampler(self) -> None:
        """(Re-)arm the sampling gate after boot restore/replay: push the
        host mirror's tables to the device leaves and attach the sampler
        to the ingest funnel. No-op when the tier is off."""
        if self.sampler is None:
            return
        self.agg.set_sampler_tables(
            self.sampler.rate, self.sampler.tail, self.sampler.link
        )
        self.agg.sampler = self.sampler

    # -- SPI factories ---------------------------------------------------

    def span_consumer(self) -> SpanConsumer:
        return self

    def span_store(self) -> SpanStore:
        return self

    def service_and_span_names(self) -> ServiceAndSpanNames:
        return self

    def autocomplete_tags(self) -> AutocompleteTags:
        return self._archive

    # -- write path ------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> Call[None]:
        def run() -> None:
            if not spans:
                return
            # chunk: a giant POST must not exceed the device batch bound
            # (state transitions serialize on the aggregator's own lock).
            # With the sampling tier on, archive/disk retention keeps
            # only verdict-kept spans — the device (below) still ingests
            # the FULL batch so sketches see 100%.
            for lo in range(0, len(spans), self.max_batch):
                chunk = spans[lo : lo + self.max_batch]
                t0 = time.perf_counter()
                with self._intern_lock:
                    cols = pack_spans(chunk, self.vocab, self._pad)
                obs.record("pack", time.perf_counter() - t0)
                kept = chunk
                if self.agg.sampler is not None:
                    keep = self.agg.sampler.verdict_cols(cols)[: len(chunk)]
                    kept = [s for s, k in zip(chunk, keep) if k]
                if kept:
                    t0 = time.perf_counter()
                    self._archive.accept(kept).execute()
                    if self._disk is not None:
                        self._disk_append_spans(kept)
                    obs.record("archive_write", time.perf_counter() - t0)
                self.agg.ingest(cols)

        return Call.of(run)

    def _disk_append_spans(self, spans: Sequence[Span]) -> None:
        """Object-path mirror of :meth:`_disk_append_parsed`: encode each
        span once (the slow path already pays per-span object costs) so
        the disk archive is complete whichever ingest path ran. The
        intern lock covers ONLY the vocab pass — encoding and the disk
        write happen outside it, so a large object-path POST cannot
        stall line-rate ingest behind its IO (r4 review finding)."""
        from zipkin_tpu.internal.hex import normalize_trace_id
        from zipkin_tpu.model import json_v2

        n = len(spans)
        parts: List[bytes] = []
        off = np.zeros(n, np.uint32)
        ln = np.zeros(n, np.uint32)
        lanes = np.zeros((n, 4), np.uint32)  # tl0 tl1 th0 th1
        svc = np.zeros(n, np.uint32)
        rsvc = np.zeros(n, np.uint32)
        name = np.zeros(n, np.uint32)
        key = np.zeros(n, np.uint32)
        ts_min = np.zeros(n, np.uint32)
        dur = np.zeros(n, np.uint64)
        err = np.zeros(n, bool)
        pos = 0
        for i, s in enumerate(spans):
            enc = json_v2.encode_span(s)
            parts.append(enc)
            off[i] = pos
            ln[i] = len(enc)
            pos += len(enc)
            full = int(normalize_trace_id(s.trace_id), 16)
            lo64, hi64 = full & ((1 << 64) - 1), full >> 64
            lanes[i] = (
                lo64 & 0xFFFFFFFF, lo64 >> 32,
                hi64 & 0xFFFFFFFF, hi64 >> 32,
            )
            ts_min[i] = (s.timestamp or 0) // 60_000_000
            dur[i] = s.duration or 0
            err[i] = "error" in (s.tags or {})
        with self._intern_lock:
            for i, s in enumerate(spans):
                sid = self.vocab.services.intern(s.local_service_name)
                rid = self.vocab.services.intern(s.remote_service_name)
                nid = self.vocab.span_names.intern(s.name)
                svc[i], rsvc[i], name[i] = sid, rid, nid
                key[i] = self.vocab.key_id(sid, nid)
        self._track_remotes(svc, rsvc)
        self._disk.append_batch(
            b"".join(parts), off, ln,
            lanes[:, 0], lanes[:, 1], lanes[:, 2], lanes[:, 3],
            svc, rsvc, name, key, ts_min, dur, err,
        )
        self._persist_archive_vocab()

    def ingest_json_fast(self, data: bytes, sampler=None):
        """Line-rate ingest: raw JSON v2 OR proto3 ``ListOfSpans`` bytes
        -> device aggregates via the native columnar parser (format
        sniffed by first byte), skipping Span objects for the bulk of
        the stream. A trace-affine 1/N sample IS archived at full fidelity
        (the parser records each span's byte extent; sampled slices are
        re-decoded by the reference codec), so ``/api/v2/trace/{id}`` and
        search stay alive in fast mode — the round-1 gap where the
        benchmark configuration and the queryable configuration were
        different systems. N = TPU_FAST_ARCHIVE_SAMPLE (default 64,
        0 disables).

        Returns (accepted, sample_dropped), or None when the native path
        can't take this payload (caller falls back to the object path).
        """
        work = self._fast_parse(data, sampler)
        if work is None:
            return None
        accepted, dropped, chunks = work
        for parsed, cols in chunks:
            self._fast_dispatch(parsed, cols)
        return accepted, dropped

    def _fast_parse(self, data: bytes, sampler=None):
        """Host half of the fast path: native parse + intern + sample +
        chunk + columnar pack. Returns (accepted, dropped, [(parsed,
        cols), ...]) or None for payloads the fast parser can't take.
        Split from :meth:`_fast_dispatch` so AsyncIngestFeeder can run
        the two halves in separate pipeline stages."""
        from zipkin_tpu import native
        from zipkin_tpu.tpu.columnar import pack_parsed

        if not native.available():
            return None
        with self._intern_lock:
            if self._nvocab is None:
                self._nvocab = native.NativeVocab(self.vocab)
            t0 = time.perf_counter()
            self._nvocab.ensure_synced()
            parsed = native.parse_spans(data, nvocab=self._nvocab)
            if parsed is None:
                return None
            self._nvocab.sync()
            obs.record("parse", time.perf_counter() - t0)
            n = parsed.n
            dropped = 0
            if sampler is not None and sampler.rate < 1.0 and n:
                keep = native.sampler_keep(parsed, n, sampler._boundary)
                dropped = int(n - keep.sum())
                if dropped:
                    idx = np.nonzero(keep)[0]
                    for field in _PARSED_FIELDS:
                        col = getattr(parsed, field, None)
                        if col is not None:
                            setattr(parsed, field, col[:n][idx])
                    parsed.n = n = len(idx)
            if n == 0:
                return 0, dropped, []
            chunks = []
            t0 = time.perf_counter()
            for lo_i in range(0, n, self.max_batch):
                hi_i = min(lo_i + self.max_batch, n)
                if lo_i == 0 and hi_i == n:
                    sub = parsed
                else:
                    sub = native.ParsedColumns()
                    sub.data = parsed.data
                    for f in _PARSED_FIELDS:
                        col = getattr(parsed, f, None)
                        setattr(sub, f, None if col is None else col[lo_i:hi_i])
                    sub.n = hi_i - lo_i
                chunks.append((sub, pack_parsed(sub, self.vocab, self._pad)))
            obs.record("pack", time.perf_counter() - t0)
        return n, dropped, chunks

    def _fast_dispatch(self, parsed, cols) -> None:
        """Device half of the fast path: raw-span archive + sharded ingest.

        With the sampling tier armed, the archive halves see only the
        verdict-kept spans (the cols lane order matches the parsed lane
        order, so one verdict pass gates both); ``agg.ingest`` still
        feeds the FULL batch so the device sketches stay unbiased."""
        keep = None
        if self.agg.sampler is not None:
            keep = self.agg.sampler.verdict_cols(cols)[: parsed.n]
        retained = self._sampled_parsed(parsed, keep)
        t0 = time.perf_counter()
        if self._disk is not None:
            self._disk_append_parsed(retained)
            if self.autocomplete_keys:
                # autocompleteTags is served from the RAM archive only
                # (the disk index has no tag lanes): keep the 1-in-N
                # sample flowing or fast-path traffic would never
                # surface tag values (ADVICE r4)
                self._archive_fast_sample(retained, retained.n)
        else:
            self._archive_fast_sample(retained, retained.n)
        obs.record("archive_write", time.perf_counter() - t0)
        if self.shadow is not None:
            # ground-truth tap: the shadow audits the same full batch
            # the device sketches see (pre-retention), O(1) append
            self.shadow.offer_cols(cols)
        self.agg.ingest(cols)

    def _sampled_parsed(self, parsed, keep):
        """Filter a ParsedColumns view down to verdict-kept lanes (the
        same hole-punching shape the boundary sampler uses in
        :meth:`_fast_parse`; archive.parsed_record compacts the byte
        holes). ``keep=None`` (sampling off) or all-kept returns the
        input untouched."""
        if keep is None or bool(keep.all()):
            return parsed
        from zipkin_tpu import native

        idx = np.nonzero(keep)[0]
        sub = native.ParsedColumns()
        sub.data = parsed.data
        for f in _PARSED_FIELDS:
            col = getattr(parsed, f, None)
            setattr(sub, f, None if col is None else col[: parsed.n][idx])
        sub.n = len(idx)
        return sub

    def _disk_append_parsed(self, parsed) -> None:
        """Write one fast-path chunk's raw spans + index columns to the
        disk archive. A chunk's spans are contiguous in the payload, so
        only that byte range is written (no duplication when a giant
        payload chunks); sampler-punched holes compact to the kept
        slices (see archive.parsed_record)."""
        from zipkin_tpu.tpu.archive import parsed_record

        rec = parsed_record(parsed)
        if rec is None:
            return
        self.disk_append_record(rec)

    def disk_append_record(self, rec: tuple) -> None:
        """Append one prebuilt archive record (archive.parsed_record
        tuple, GLOBAL vocab ids) — the seam the MP dispatcher uses to
        feed worker-parsed batches into the disk archive."""
        svc, rsvc = rec[7], rec[8]
        self._track_remotes(svc, rsvc)
        self._disk.append_batch(*rec)
        self._persist_archive_vocab()

    def _track_remotes(self, svc: np.ndarray, rsvc: np.ndarray) -> None:
        pairs = np.unique(
            svc.astype(np.uint64) << np.uint64(32) | rsvc.astype(np.uint64)
        )
        with self._names_lock:
            for p in pairs.tolist():
                s, r = p >> 32, p & 0xFFFFFFFF
                if s:
                    self._local_svc_ids.add(int(s))
                if s and r:
                    self._remote_by_svc.setdefault(int(s), set()).add(int(r))

    def warm(self, data: bytes) -> None:
        """Compile every ingest-path program against a real payload (the
        sample is INGESTED repeatedly — serving/benchmark warm-up only).
        Remote compiles take minutes and must precede any timed window."""
        work = self._fast_parse(data)
        if work is None:
            # payload the fast parser can't take: warm through the object
            # path instead — this still must reach agg.warm_programs or
            # the fused/flush/rollup programs first-compile mid-traffic
            from zipkin_tpu.model import codec
            from zipkin_tpu.tpu.columnar import pack_spans

            spans = codec.decode_spans(data)
            self._archive.accept(spans).execute()
            with self._intern_lock:
                cols = pack_spans(
                    spans[: self.max_batch], self.vocab, self._pad
                )
            self.agg.warm_programs(cols)
            return
        _, _, chunks = work
        if chunks:
            self.agg.warm_programs(chunks[0][1])

    def _archive_fast_sample(self, parsed, n: int) -> None:
        """Archive a trace-affine 1/N sample of a fast-ingest batch at
        full fidelity by re-decoding each sampled span's exact JSON slice
        (extents recorded by the native parser)."""
        every = self._fast_archive_every
        if every <= 0:
            return
        from zipkin_tpu.tpu.columnar import _mix32

        tid = (
            parsed.tl0[:n] ^ parsed.tl1[:n] ^ parsed.th0[:n] ^ parsed.th1[:n]
        )
        pick = np.nonzero(_mix32(tid) % np.uint32(every) == 0)[0]
        if not len(pick):
            return
        data = parsed.data
        off, ln = parsed.span_off, parsed.span_len
        spans = []
        for i in pick:
            try:
                # format-aware: fast-path slices are JSON objects or
                # proto3 Span messages, whichever wire ingested them
                spans.append(
                    _decode_raw_span(bytes(data[off[i] : off[i] + ln[i]]))
                )
            except Exception:  # a slice the strict codec rejects: skip
                continue
        if spans:
            self._archive.accept(spans).execute()

    # -- raw trace reads: disk archive + host archive ---------------------

    def _disk_trace_spans(self, trace_id: str, views=None) -> List[Span]:
        """Decode every archived span matching ``trace_id`` under the
        store's strictness (exact low-64 match; high lanes + the decoded
        id string verified when strict). Pass ``views`` (an archive
        ``views()`` snapshot) when calling in a loop — without it every
        call re-sorts the live segment (the 1881-argsort search the
        views() docstring records)."""
        from zipkin_tpu.internal.hex import normalize_trace_id
        from zipkin_tpu.model import json_v2

        normalized = normalize_trace_id(trace_id)
        full = int(normalized, 16)
        lo, hi = full & ((1 << 64) - 1), full >> 64
        slices = self._disk.fetch_trace_raw(
            lo & 0xFFFFFFFF, lo >> 32, hi & 0xFFFFFFFF, hi >> 32,
            strict=self.strict_trace_id, views=views,
        )
        spans = []
        for raw in slices:
            try:
                s = _decode_raw_span(raw)
            except Exception:  # pragma: no cover - parser accepted it
                continue
            if self.strict_trace_id and normalize_trace_id(
                s.trace_id
            ) != normalized:
                continue
            spans.append(s)
        return spans

    def get_trace(self, trace_id: str) -> Call[List[Span]]:
        if self._disk is None:
            return self._archive.get_trace(trace_id)

        def run() -> List[Span]:
            from zipkin_tpu.internal.span_node import merge_trace

            spans = self._disk_trace_spans(trace_id)
            spans += self._archive.get_trace(trace_id).execute()
            return merge_trace(spans)

        return Call.of(run)

    def get_traces(self, trace_ids: Sequence[str]) -> Call[List[List[Span]]]:
        if self._disk is None:
            return self._archive.get_traces(trace_ids)

        def run() -> List[List[Span]]:
            from zipkin_tpu.storage.spi import trace_id_key

            out, seen = [], set()
            for tid in trace_ids:
                key = trace_id_key(tid, self.strict_trace_id)
                if key in seen:
                    continue
                seen.add(key)
                spans = self.get_trace(tid).execute()
                if spans:
                    out.append(spans)
            return out

        return Call.of(run)

    def get_traces_query(self, request: QueryRequest) -> Call[List[List[Span]]]:
        if self._disk is None:
            return self._archive.get_traces_query(request)

        def run() -> List[List[Span]]:
            if not self.search_enabled:
                return []
            return self._disk_query(request)

        return Call.of(run)

    def _disk_query(self, request: QueryRequest) -> List[List[Span]]:
        """getTraces over the disk archive: vectorized candidate masks on
        the INDEXED columns (service/span-name/remote/duration/window),
        then decode candidate traces and apply the exact
        ``QueryRequest.test`` predicate — annotationQuery and every other
        non-indexed clause are exact by post-filtering, the reference's
        fetch-then-filter row-store shape. Candidates scan newest
        segments first; if the post-filter starves the limit the scan
        widens once (the bounded-scan trade of a windowed store)."""
        from zipkin_tpu.internal.span_node import merge_trace
        from zipkin_tpu.model import json_v2
        from zipkin_tpu.storage.spi import group_by_trace_id, trace_id_key

        svc_id = rsvc_id = name_id = None
        if request.service_name:
            svc_id = self.vocab.services.get(request.service_name.lower())
            if svc_id is None:
                return []
        if request.remote_service_name:
            rsvc_id = self.vocab.services.get(
                request.remote_service_name.lower()
            )
            if rsvc_id is None:
                return []
        if request.span_name:
            name_id = self.vocab.span_names.get(request.span_name.lower())
            if name_id is None:
                return []
        lo_min = epoch_minutes(request.end_ts - request.lookback)
        hi_min = epoch_minutes(request.end_ts)

        def fetch(cand_limit: int) -> Tuple[List[List[Span]], bool]:
            # ONE view snapshot for the whole query: the live segment
            # sorts its rows when a view is taken, so per-trace
            # re-snapshots would re-sort per candidate
            views = self._disk.views()
            cands = self._disk.candidate_trace_ids(
                ts_lo_min=lo_min, ts_hi_min=hi_min,
                svc_id=svc_id, rsvc_id=rsvc_id, name_id=name_id,
                min_dur=request.min_duration, max_dur=request.max_duration,
                limit=cand_limit, views=views,
            )
            # RAM-archive union first (object-path spans of the same
            # traces plus traces only it holds) — cheap, no disk IO
            ram: dict = {}
            for trace in self._archive.get_traces_query(request).execute():
                key = trace_id_key(trace[0].trace_id, self.strict_trace_id)
                ram.setdefault(key, []).extend(trace)
            # INCREMENTAL candidate processing (r5, VERDICT r4 order 6's
            # other half): candidates arrive newest-first, so fetching
            # + decoding stops once `limit` traces PASS the exact
            # predicate — a broad query (e.g. service-only) decodes
            # ~limit traces, not the whole cand_limit over-fetch. The
            # bounded-scan trade is unchanged: a trace whose candidate
            # ts is older than the collected set but whose max span ts
            # is newer can still be missed, exactly as when cand_limit
            # bounded the scan.
            out = []
            seen_keys: set = set()
            for id64, _ in cands:
                if len(out) >= request.limit:
                    break
                raw = self._disk.fetch_trace_raw(
                    id64 & 0xFFFFFFFF, id64 >> 32, 0, 0, strict=False,
                    views=views,
                )
                spans = []
                for r in raw:
                    try:
                        spans.append(_decode_raw_span(r))
                    except Exception:  # pragma: no cover
                        continue
                for group in group_by_trace_id(spans, self.strict_trace_id):
                    key = trace_id_key(
                        group[0].trace_id, self.strict_trace_id
                    )
                    if key in seen_keys:
                        continue
                    seen_keys.add(key)
                    merged = merge_trace(group + ram.pop(key, []))
                    if request.test(merged):
                        out.append(merged)
            # Traces the disk walk never touched but the RAM archive
            # matched: their spans may ALSO exist on disk (an early
            # break above skips candidates once `limit` passed), so
            # fetch the disk half by trace id before merging — a
            # returned trace is always complete, never RAM-only
            # (r5 review finding). Bounded: the RAM query returns at
            # most `limit` traces.
            for key, spans in ram.items():
                merged = merge_trace(
                    spans
                    + self._disk_trace_spans(spans[0].trace_id, views=views)
                )
                if request.test(merged):
                    out.append(merged)
            out.sort(
                key=lambda t: max((s.timestamp or 0) for s in t),
                reverse=True,
            )
            return out[: request.limit], len(cands) >= cand_limit

        results, capped = fetch(request.limit * 4 + 16)
        if capped and len(results) < request.limit:
            # the post-filter starved the limit inside the first scan
            # window: widen once before settling for fewer results
            results, _ = fetch((request.limit * 4 + 16) * 8)
        return results

    def get_service_names(self) -> Call[List[str]]:
        if self._disk is None:
            return self._archive.get_service_names()

        def run() -> List[str]:
            if not self.search_enabled:
                return []
            # ids seen as a LOCAL service (remote names share the vocab
            # table but must not list — upstream ServiceAndSpanNames
            # semantics); bounded by max_services, listed without a
            # retention cutoff
            with self._names_lock:
                ids = list(self._local_svc_ids)
            names = {self.vocab.services.lookup(s) for s in ids}
            return sorted(n for n in names if n)

        return Call.of(run)

    def get_remote_service_names(self, service_name: str) -> Call[List[str]]:
        if self._disk is None:
            return self._archive.get_remote_service_names(service_name)

        def run() -> List[str]:
            if not self.search_enabled:
                return []
            sid = self.vocab.services.get(service_name.lower())
            with self._names_lock:
                rids = list(self._remote_by_svc.get(sid or -1, ()))
            names = {self.vocab.services.lookup(r) for r in rids}
            names |= set(
                self._archive.get_remote_service_names(service_name).execute()
            )
            return sorted(n for n in names if n)

        return Call.of(run)

    def get_span_names(self, service_name: str) -> Call[List[str]]:
        if self._disk is None:
            return self._archive.get_span_names(service_name)

        def run() -> List[str]:
            if not self.search_enabled:
                return []
            sid = self.vocab.services.get(service_name.lower())
            if sid is None:
                return []
            with self.vocab._lock:
                pairs = list(self.vocab._key_list)
            names = {
                self.vocab.span_names.lookup(nid)
                for s, nid in pairs
                if s == sid
            }
            return sorted(n for n in names if n)

        return Call.of(run)

    def get_keys(self) -> Call[List[str]]:
        return self._archive.get_keys()

    def get_values(self, key: str) -> Call[List[str]]:
        return self._archive.get_values(key)

    # -- aggregate reads: device ----------------------------------------

    def _cached_read(self, key: str, compute):
        """Memoize a device pull until the next QUERY-VISIBLE state
        mutation: the aggregator bumps write_version on step, rollup and
        restore — deliberately NOT on a digest flush, which changes no
        answer (the pend-fold and no-pend reads are bit-identical), so a
        read-triggered flush keeps every cached answer valid. The whole
        cache drops when the version advances — keys embed window
        minutes and quantile lists, so per-key staleness checks alone
        would let dead entries accumulate forever under a polling UI.

        Brownout read modes (runtime/overload.py, ISSUE 13): under
        B1/B2 (``cache_first``) a version-stale entry still serves if
        younger than the controller's staleness bound — the device pull
        it avoids would queue behind a saturated ingest lock; under B3
        (``cache_only``) any cached answer serves. Entries carry the
        write version they were computed at, so the staleness of every
        serve is exact; a cold key still computes (serving an error
        would turn a brownout into an outage for first-touch queries),
        and the first normal-mode read after recovery drops every
        stale entry wholesale."""
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        version = self.agg.write_version
        ctl = self.overload
        mode = ctl.read_mode() if ctl is not None else "normal"
        with self._read_cache_lock:
            if mode == "normal" and self._read_cache_version != version:
                self._read_cache.clear()
                self._read_cache_version = version
            hit = self._read_cache.get(key)
            if hit is not None:
                value, born, born_version = hit
                age_ms = (time.monotonic() - born) * 1000.0
                fresh = born_version == version
                serve = fresh or mode == "cache_only" or (
                    mode == "cache_first"
                    and age_ms <= ctl.max_stale_ms
                )
                if serve:
                    if not fresh:
                        self._read_cache_stale_serves += 1
                    self._read_cache_age_ms = age_ms
                    if age_ms > self._read_cache_age_max_ms:
                        self._read_cache_age_max_ms = age_ms
                    obs.record("query_cached", time.perf_counter() - t0)
                    querytrace.stamp_active(
                        querytrace.QSEG_CACHE_PROBE, t0_ns,
                        time.perf_counter_ns(),
                    )
                    return value
        # the probe segment ends where compute() begins — on a miss the
        # rest of the wall belongs to dispatch/transfer/unpack stamps
        querytrace.stamp_active(
            querytrace.QSEG_CACHE_PROBE, t0_ns, time.perf_counter_ns()
        )
        value = compute()
        obs.record("query_fresh", time.perf_counter() - t0)
        with self._read_cache_lock:
            if mode != "normal" or self._read_cache_version == version:
                self._read_cache[key] = (value, time.monotonic(), version)
        return value

    def invalidate_read_cache(self) -> None:
        """Drop memoized device pulls, including cached dependency
        answers (keeps the aggregator's link context). For harnesses
        that must re-measure device reads."""
        with self._read_cache_lock:
            self._read_cache.clear()
            self._deps_cache.clear()

    # -- epoch-published read mirror (tpu/mirror.py, ISSUE 14) -----------

    def _seed_mirror(self) -> None:
        """Pin the dashboard's default reads into the mirror's demand
        registry so the FIRST publish (boot, before the ticker starts)
        already carries them — the first post-boot dashboard refresh is
        lock-free, not a warming miss. Keys match `_cached_read`'s so
        mirror and fresh paths memoize the same computes."""
        qs = DEFAULT_QS
        qkey = ",".join(f"{q:.6g}" for q in qs)
        self.mirror.register(
            f"overview:{qkey}",
            lambda: self.agg.sketch_overview(qs), pinned=True,
        )
        self.mirror.register(
            "card", lambda: self.agg.cardinalities(), pinned=True,
        )
        self.mirror.register(
            f"quant:digest:{qkey}",
            lambda: self.agg.quantiles(qs, source="digest"), pinned=True,
        )

    def publish_mirror(self, force: bool = False,
                       paced: bool = False) -> bool:
        """One mirror epoch (see ReadMirror.publish): the windows ticker
        calls this each tick (``paced=True`` — the duty-cycle cap); the
        resume adapter calls it at boot. Reader-process demand drains
        FIRST, so a key a reader missed is carried by this very epoch —
        a shm-side miss costs one tick, like an in-process miss costs
        one lock-path read."""
        pub = self._segment_publisher
        if pub is not None:
            for key in pub.drain_demand():
                self.mirror_register_key(key)
        return self.mirror.publish(force=force, paced=paced)

    def attach_mirror_segment(self, segment) -> None:
        """Wire a shm mirror segment (serving/segment.py) into the
        publish path: each ReadMirror epoch is sanitized + serialized
        into the segment AFTER the snapshot swap — outside the
        aggregator lock, so publication stays ONE hold per tick. Call
        before the boot publish so crash-resume readers attach to a
        segment that already carries the restored epoch."""
        from zipkin_tpu.serving.publisher import SegmentPublisher

        pub = SegmentPublisher(segment)
        self._segment = segment
        self._segment_publisher = pub

        def sink(snap) -> None:
            tt = self.timetier
            pub.publish_snapshot(
                snap,
                vocab=self.vocab,
                max_stale_ms=self.mirror.max_stale_ms,
                deps_max_stale_ms=self._deps_max_stale_ms,
                time_bucket_minutes=self.config.time_bucket_minutes,
                global_hll_row=self.config.global_hll_row,
                tt_sealed_through=(
                    tt.sealed_through if tt is not None else None
                ),
                counters=self.ingest_counters(),
                mirror_generation=self.mirror.gen,
            )

        self.mirror.segment_sink = sink

    def mirror_register_key(self, key: str) -> bool:
        """Parse a reader-demanded mirror key string back into its
        compute closure and register it (unpinned, TTL'd — exactly the
        PR 14 demand-registry contract). The grammar is the closed set
        of key forms the store itself mints; anything else (including
        tenant-prefixed keys, whose scoped read planes do not exist
        yet) is refused and counted, never guessed at."""
        try:
            if key == "card":
                return self.mirror.register(
                    key, lambda: self.agg.cardinalities()
                )
            if key.startswith("overview:"):
                qs = tuple(
                    float(x) for x in key.split(":", 1)[1].split(",") if x
                )
                if qs:
                    return self.mirror.register(
                        key, lambda: self.agg.sketch_overview(qs)
                    )
            if key.startswith("quant:w:"):
                _, _, lo, hi, qstr = key.split(":", 4)
                lo_min, hi_min = int(lo), int(hi)
                qs = tuple(float(x) for x in qstr.split(",") if x)
                if qs:
                    return self.mirror.register(
                        key,
                        lambda: self.agg.quantiles(
                            qs, ts_lo_min=lo_min, ts_hi_min=hi_min
                        ),
                    )
            elif key.startswith("quant:"):
                _, src, qstr = key.split(":", 2)
                qs = tuple(float(x) for x in qstr.split(",") if x)
                if src in ("digest", "hist") and qs:
                    return self.mirror.register(
                        key, lambda: self.agg.quantiles(qs, source=src)
                    )
            if key.startswith("deps:"):
                _, lo, hi = key.split(":")
                lo_min, hi_min = int(lo), int(hi)
                return self.mirror.register(
                    key, lambda: self._dependency_links(lo_min, hi_min)
                )
            if key.startswith("ttq:") and self.timetier is not None:
                _, lo, hi = key.split(":")
                lo_ep, hi_ep = int(lo), int(hi)
                return self.mirror.register(
                    key,
                    lambda: self.timetier.window(self.agg, lo_ep, hi_ep),
                )
        except (ValueError, TypeError):
            pass
        self._demand_unparsed += 1
        return False

    def _mirror_bound(
        self, staleness_ms: Optional[float], default_ms: float
    ):
        """Fold the per-request staleness bound with the brownout read
        mode into ONE effective bound: ms the serve may be stale, None
        for any age (B3 cache-only), or _MIRROR_FRESH when the request
        opted out (``staleness_ms <= 0`` — the escape hatch for
        staleness-intolerant queries). Under B1/B2 cache-first the
        controller's bound can only LOOSEN the request's — brownout
        never makes answers fresher, it keeps them cheap."""
        if staleness_ms is not None and staleness_ms <= 0:
            return _MIRROR_FRESH
        bound = (
            float(staleness_ms) if staleness_ms is not None
            else float(default_ms)
        )
        ctl = self.overload
        mode = ctl.read_mode() if ctl is not None else "normal"
        if mode == "cache_first":
            bound = max(bound, float(ctl.max_stale_ms))
        elif mode == "cache_only":
            return None
        return bound

    def _mirror_serve(self, key: str, bound_ms, allow_stale: bool = True):  # zt-mirror-served: the whole point — a mirror serve must never acquire the aggregator lock (ZT10)
        """Serve ``key`` from the published mirror epoch, entirely
        lock-free: seqlock snapshot read, staleness check against the
        live write_version, stamp + record. None on a miss (caller
        falls through to the lock path and registers demand)."""
        mirror = self.mirror
        if mirror is None or not mirror.enabled:
            return None
        t0 = time.perf_counter()
        t0_ns = time.perf_counter_ns()
        hit = mirror.serve(
            key, bound_ms, self.agg.write_version, allow_stale
        )
        if hit is None:
            return None
        obs.record("query_mirror", time.perf_counter() - t0)
        querytrace.stamp_active(
            querytrace.QSEG_MIRROR_SERVE, t0_ns, time.perf_counter_ns()
        )
        return hit

    def _mirror_allow_stale(self, staleness_ms) -> bool:
        """May THIS request see a version-stale epoch? Yes when the
        caller opted in (explicit positive ``staleness_ms``), a
        brownout read mode is in force, or the aggregator lock is
        contended right now (non-blocking probe) — otherwise an exact
        read is cheap and default requests stay exact, the same
        posture ``_cached_read`` takes outside brownout. The probe is
        deliberately last: single-threaded callers never pay it a
        surprise stale answer, and under the load the mirror exists
        for, it is what keeps readers off the lock."""
        if staleness_ms is not None:
            return True
        ctl = self.overload
        if ctl is not None and ctl.read_mode() != "normal":
            return True
        probe = getattr(self.agg.lock, "would_block", None)
        return bool(probe is not None and probe())

    def _mirror_read(self, key: str, compute, staleness_ms=None):
        """Mirror-first read: serve lock-free from the published epoch
        when the age allows; otherwise register the key for the next
        epoch and fall through to the versioned read cache (which is
        where the aggregator lock — and the brownout cache-first logic
        for version-stale entries — lives). A cold key still computes
        fresh, so a brownout never turns into an outage for
        first-touch queries."""
        bound = self._mirror_bound(staleness_ms, self.mirror.max_stale_ms)
        if bound is not _MIRROR_FRESH:
            hit = self._mirror_serve(
                key, bound, self._mirror_allow_stale(staleness_ms)
            )
            if hit is not None:
                return hit[0]
            self.mirror.register(key, compute)
        return self._cached_read(key, compute)

    # -- time-disaggregated sketch tier (tpu/timetier.py, ISSUE 15) ------

    def tt_seal(self, limit: Optional[int] = None) -> int:
        """Ticker seam: seal every finished device time bucket into the
        host time tier (the windows ticker calls this each tick, next
        to publish_mirror). Returns segments sealed; 0 when the tier is
        disabled (``time_buckets=0``) or nothing is due."""
        if self.timetier is None:
            return 0
        return self.timetier.seal_up_to(self.agg, limit=limit)

    def _tt_epochs(self, end_ts: int, lookback: Optional[int]):
        """Bucket-aligned epoch range for a windowed sketch read — the
        mirror-key canonicalization: every (endTs, lookback) pair whose
        endpoints land in the same time buckets maps to the same
        (lo_ep, hi_ep), so a polling client stepping endTs by seconds
        reuses ONE ``ttq:`` demand key instead of registering a fresh
        key (and a fresh publish-time merge) per request."""
        g = self.config.time_bucket_minutes
        lb = lookback if lookback is not None else end_ts
        lo_ep = max(0, epoch_minutes(end_ts - lb) // g)
        hi_ep = max(0, epoch_minutes(end_ts) // g)
        return lo_ep, hi_ep

    def _tt_window(self, lo_ep: int, hi_ep: int, staleness_ms=None):
        """Mirror-first windowed sketch read: ONE demand key per
        bucket-aligned epoch range (``ttq:<lo_ep>:<hi_ep>``) carrying
        the merged WindowAnswer for all three windowed routes
        (quantiles, cardinalities, dependencies). A sealed-only
        window's compute never touches the aggregator lock; a range
        reaching past ``sealed_through`` re-enters it only for the one
        packed device pull of the unsealed current bucket."""
        key = f"ttq:{lo_ep}:{hi_ep}"
        return self._mirror_read(
            key,
            # lambda derefs self.agg/self.timetier at CALL time
            # (clear() swaps the aggregator wholesale)
            lambda: self.timetier.window(self.agg, lo_ep, hi_ep),
            staleness_ms,
        )

    def _tt_dependency_links(self, ans) -> List[DependencyLink]:
        """Shape a merged WindowAnswer's dense edge planes into API
        links (the dense-pull shaping from _dependency_links)."""
        t0_ns = time.perf_counter_ns()
        s = self.config.max_services
        dense_c = np.asarray(ans.calls)
        dense_e = np.asarray(ans.errs)
        p_idx, c_idx = np.nonzero(dense_c)
        out: List[DependencyLink] = []
        for p, c in zip(p_idx, c_idx):
            parent = self.vocab.services.lookup(int(p))
            child = self.vocab.services.lookup(int(c))
            if not parent or not child:
                continue
            out.append(
                DependencyLink(
                    parent=parent,
                    child=child,
                    call_count=int(dense_c[p, c]),
                    error_count=int(dense_e[p, c]),
                )
            )
        querytrace.stamp_active(
            querytrace.QSEG_LINK_RESOLVE, t0_ns, time.perf_counter_ns()
        )
        return out

    def get_dependencies(
        self, end_ts: int, lookback: int,
        staleness_ms: Optional[float] = None,
    ) -> Call[List[DependencyLink]]:
        def run() -> List[DependencyLink]:
            qt = self.querytrace.begin("dependencies")
            try:
                return self._get_dependencies(end_ts, lookback, staleness_ms)
            finally:
                self.querytrace.finish(qt)

        return Call.of(run)

    def _get_dependencies(
        self, end_ts: int, lookback: int,
        staleness_ms: Optional[float] = None,
    ) -> List[DependencyLink]:
            tt = self.timetier
            if tt is not None:
                lo_ep, hi_ep = self._tt_epochs(end_ts, lookback)
                if lo_ep <= tt.sealed_through:
                    # time-tier route (ISSUE 15): some of the window is
                    # already sealed — merge the covering segments
                    # host-side (exact per-bucket edge counts, verified
                    # bit-equal to the dense ring pull) instead of
                    # re-sorting the span ring; windows the sealer has
                    # not reached yet stay on the ring path below
                    ans = self._tt_window(lo_ep, hi_ep, staleness_ms)
                    return self._tt_dependency_links(ans)
            lo_min = epoch_minutes(end_ts - lookback)
            hi_min = epoch_minutes(end_ts)
            # mirror-first: the published epoch carries the final link
            # list (resolved on the publisher thread), so a hit returns
            # without touching the aggregator lock OR the deps cache.
            # Dependencies already tolerate bounded staleness by design
            # (the reference's table is an offline batch job), so the
            # deps bound — not the general mirror bound — is the default.
            bound = self._mirror_bound(staleness_ms, self._deps_max_stale_ms)
            if bound is not _MIRROR_FRESH:
                mkey = f"deps:{lo_min}:{hi_min}"
                hit = self._mirror_serve(mkey, bound)
                if hit is not None:
                    return hit[0]
                self.mirror.register(
                    mkey,
                    lambda: self._dependency_links(lo_min, hi_min),
                )
            fresh = self.agg.write_version
            now = time.monotonic()
            t0 = time.perf_counter()
            t0_ns = time.perf_counter_ns()
            with self._read_cache_lock:
                hit = self._deps_cache.get((lo_min, hi_min))
                if hit is not None:
                    value, version, t = hit
                    if version == fresh or (
                        (now - t) * 1000.0 < self._deps_max_stale_ms
                    ):
                        age_ms = (now - t) * 1000.0
                        self._read_cache_age_ms = age_ms
                        if age_ms > self._read_cache_age_max_ms:
                            self._read_cache_age_max_ms = age_ms
                        obs.record("query_cached", time.perf_counter() - t0)
                        querytrace.stamp_active(
                            querytrace.QSEG_CACHE_PROBE, t0_ns,
                            time.perf_counter_ns(),
                        )
                        return value
            querytrace.stamp_active(
                querytrace.QSEG_CACHE_PROBE, t0_ns, time.perf_counter_ns()
            )
            value = self._compute_dependencies(lo_min, hi_min)
            with self._read_cache_lock:
                self._deps_cache[(lo_min, hi_min)] = (value, fresh, now)
                # prune by age so shifting endTs windows can't grow this
                stale = [
                    k for k, (_, _, t) in self._deps_cache.items()
                    if (now - t) * 1000.0 >= self._deps_max_stale_ms
                ]
                for k in stale:
                    if k != (lo_min, hi_min):
                        del self._deps_cache[k]
            return value

    def _compute_dependencies(
        self, lo_min: int, hi_min: int
    ) -> List[DependencyLink]:
        return self._dependency_links(
            lo_min, hi_min, fetch=self._cached_read
        )

    def _dependency_links(
        self, lo_min: int, hi_min: int, fetch=None
    ) -> List[DependencyLink]:
            # edge pull + vocab resolution, parameterized by the fetch
            # seam: the query path memoizes through _cached_read; the
            # mirror publisher (already holding the aggregator lock for
            # its one epoch hold) calls the aggregator directly so a
            # publish never populates the versioned read cache
            if fetch is None:
                def fetch(_key, compute):
                    return compute()
            # edges compacted on device: [E] vectors, not dense [S, S]
            idx, calls, errors = fetch(
                f"edges:{lo_min}:{hi_min}",
                lambda: self.agg.dependency_edges(lo_min, hi_min),
            )
            s = self.config.max_services
            live = calls > 0
            if bool(live.all()) and len(calls) < s * s:
                # every top-k slot is occupied: the graph has more edges
                # than the compaction width — fall back to the dense
                # matrices so no edge is silently dropped (the compact
                # path stays the common case; real service graphs are
                # sparse)
                logger.debug(
                    "dependency edge compaction full (%d); using dense pull",
                    len(calls),
                )
                lo2, hi2 = lo_min, hi_min
                dense_c, dense_e = fetch(
                    f"depmat:{lo2}:{hi2}",
                    lambda: self.agg.dependency_matrices(lo2, hi2),
                )
                p_idx, c_idx = np.nonzero(dense_c)
                flat_idx = p_idx * s + c_idx
                idx, calls, errors = (
                    flat_idx, dense_c[p_idx, c_idx], dense_e[p_idx, c_idx]
                )
                live = calls > 0
            t0_ns = time.perf_counter_ns()
            out: List[DependencyLink] = []
            for flat, n_calls, n_errs in zip(idx[live], calls[live], errors[live]):
                parent = self.vocab.services.lookup(int(flat) // s)
                child = self.vocab.services.lookup(int(flat) % s)
                if not parent or not child:
                    continue
                out.append(
                    DependencyLink(
                        parent=parent,
                        child=child,
                        call_count=int(n_calls),
                        error_count=int(n_errs),
                    )
                )
            querytrace.stamp_active(
                querytrace.QSEG_LINK_RESOLVE, t0_ns, time.perf_counter_ns()
            )
            return out

    def latency_quantiles(
        self,
        qs: Sequence[float],
        service_name: Optional[str] = None,
        span_name: Optional[str] = None,
        use_digest: bool = True,
        end_ts: Optional[int] = None,
        lookback: Optional[int] = None,
        staleness_ms: Optional[float] = None,
    ) -> List[dict]:
        """Latency percentile rows per (service, spanName) — the read the
        Lens duration-percentile context needs, served from sketches.

        With ``end_ts``/``lookback`` (epoch ms, as in the query API) the
        rows come from the time tier when its sealer has reached the
        window (per-bucket t-digests merged host-side over the covering
        sealed segments — ARBITRARY ranges, ISSUE 15), else from the
        time-sliced histograms covering the most recent
        T*slice_minutes of traffic (``use_digest=False`` forces the
        hist-slice path). Returns dicts: {service, spanName, count,
        quantiles: {q: µs}}.

        ``staleness_ms`` tunes the mirror-first serve: None accepts the
        mirror's published bound, a positive value tightens/loosens it
        per request, and <= 0 forces a fresh lock-path read.
        """
        qt = self.querytrace.begin("quantiles")
        try:
            if end_ts is None and lookback is not None:
                # Zipkin query convention: endTs defaults to "now" when
                # only lookback is given (QueryRequest semantics,
                # SURVEY.md §2.3)
                end_ts = int(time.time() * 1000)
            qkey = ",".join(f"{q:.6g}" for q in qs)
            if end_ts is not None:
                tt = self.timetier
                lo_ep, hi_ep = (
                    self._tt_epochs(end_ts, lookback)
                    if tt is not None else (0, -1)
                )
                if (
                    use_digest and tt is not None
                    and lo_ep <= tt.sealed_through
                ):
                    # time-tier route (ISSUE 15): per-bucket t-digests
                    # merged host-side over the covering sealed
                    # segments (ops/ttmerge.py) — arbitrary [lookback,
                    # endTs] ranges, not just the hist-slice horizon;
                    # the unsealed current bucket is the one device
                    # pull when the range reaches it
                    ans = self._tt_window(lo_ep, hi_ep, staleness_ms)
                    source_q = ttmerge.digest_quantile(ans.digest, qs)
                    counts = ttmerge.digest_total(ans.digest)
                else:
                    lb = lookback if lookback is not None else end_ts
                    lo_min = epoch_minutes(end_ts - lb)
                    hi_min = epoch_minutes(end_ts)
                    source_q, counts = self._mirror_read(
                        f"quant:w:{lo_min}:{hi_min}:{qkey}",
                        lambda: self.agg.quantiles(
                            qs, ts_lo_min=lo_min, ts_hi_min=hi_min
                        ),
                        staleness_ms,
                    )
            else:
                src = "digest" if use_digest else "hist"
                source_q, counts = self._mirror_read(
                    f"quant:{src}:{qkey}",
                    lambda: self.agg.quantiles(qs, source=src),
                    staleness_ms,
                )

            return self._quantile_rows(
                qs, source_q, counts, service_name, span_name
            )
        finally:
            self.querytrace.finish(qt)

    def _quantile_rows(
        self,
        qs: Sequence[float],
        source_q: np.ndarray,
        counts: np.ndarray,
        service_name: Optional[str],
        span_name: Optional[str],
    ) -> List[dict]:
        """Shape pulled ([K, Q], [K]) quantile arrays into API rows —
        shared by latency_quantiles and the coalesced sketch_overview."""
        t0_ns = time.perf_counter_ns()
        try:
            return self._quantile_rows_inner(
                qs, source_q, counts, service_name, span_name
            )
        finally:
            querytrace.stamp_active(
                querytrace.QSEG_SERIALIZE, t0_ns, time.perf_counter_ns()
            )

    def _quantile_rows_inner(
        self,
        qs: Sequence[float],
        source_q: np.ndarray,
        counts: np.ndarray,
        service_name: Optional[str],
        span_name: Optional[str],
    ) -> List[dict]:
        want_svc = (
            self.vocab.services.get(service_name.lower()) if service_name else None
        )
        if service_name and want_svc is None:
            return []
        # vectorized row selection over the key vocab (the round-1 per-key
        # Python loop scanned all max_keys rows per query)
        with self.vocab._lock:
            pairs = np.asarray(self.vocab._key_list, np.int32)  # [num_keys, 2]
        kids = np.arange(1, pairs.shape[0])
        mask = counts[kids] > 0
        if want_svc is not None:
            mask &= pairs[kids, 0] == want_svc
        if span_name:
            want_name = self.vocab.span_names.get(span_name.lower())
            if want_name is None:
                return []
            mask &= pairs[kids, 1] == want_name
        out = []
        for kid in kids[mask]:
            out.append(
                {
                    "serviceName": self.vocab.services.lookup(int(pairs[kid, 0])),
                    "spanName": self.vocab.span_names.lookup(int(pairs[kid, 1])),
                    "count": int(counts[kid]),
                    "quantiles": {
                        float(q): float(source_q[kid, i]) for i, q in enumerate(qs)
                    },
                }
            )
        return out

    def _cardinality_rows(self, est: np.ndarray) -> dict:
        # operating-envelope guard: past envelope_max the estimator's
        # bias exceeds half its 3σ noise gate, so the number reads as a
        # lower bound, not an estimate — count it, gauge it, say it once
        beyond = int((est > self._hll_envelope_max).sum())
        if beyond:
            self._hll_envelope_exceeded += 1
            if not self._hll_beyond_envelope_rows:
                logger.warning(
                    "%d HLL row(s) estimate beyond the p=%d operating "
                    "envelope (%.3g): bias now dominates noise; treat "
                    "these cardinalities as lower bounds",
                    beyond,
                    self.config.hll_precision,
                    self._hll_envelope_max,
                )
        self._hll_beyond_envelope_rows = beyond
        out = {"_global": float(est[self.config.global_hll_row])}
        for name in self.vocab.services.names:
            sid = self.vocab.services.get(name)
            if sid:
                out[name] = float(est[sid])
        return out

    def trace_cardinalities(
        self, staleness_ms: Optional[float] = None,
        end_ts: Optional[int] = None,
        lookback: Optional[int] = None,
    ) -> dict:
        """Estimated distinct trace counts: {"_global": n, service: n, ...}.

        With ``end_ts``/``lookback`` (epoch ms) the registers come from
        the time tier's covering bucket segments (HLL register-max
        merge, ops/ttmerge.py) — windowed cardinality over arbitrary
        ranges; without a window the all-time cumulative registers
        serve, as before."""
        qt = self.querytrace.begin("cardinalities")
        try:
            if end_ts is None and lookback is not None:
                # endTs defaults to "now" when only lookback is given
                # (QueryRequest semantics, SURVEY.md §2.3)
                end_ts = int(time.time() * 1000)
            if end_ts is not None and self.timetier is not None:
                lo_ep, hi_ep = self._tt_epochs(end_ts, lookback)
                ans = self._tt_window(lo_ep, hi_ep, staleness_ms)
                return self._cardinality_rows(ttmerge.hll_estimate(ans.hll))
            # lambda, not the bound method: a registered demand closure
            # must deref self.agg at CALL time (clear() swaps it)
            est = self._mirror_read(
                "card", lambda: self.agg.cardinalities(), staleness_ms
            )
            return self._cardinality_rows(est)
        finally:
            self.querytrace.finish(qt)

    def sketch_overview(
        self,
        qs: Sequence[float],
        service_name: Optional[str] = None,
        span_name: Optional[str] = None,
        staleness_ms: Optional[float] = None,
    ) -> dict:
        """Everything the UI sketch page shows, from ONE device dispatch
        and ONE device→host transfer: {"percentiles": latency_quantiles
        rows, "cardinalities": trace_cardinalities dict, "counters":
        ingest_counters dict}. Replaces three aggregator reads (and three
        HTTP round trips) per page refresh. Mirror-served by default:
        the raw packed triple comes from the published epoch (row
        shaping and the live counters dict still run per request)."""
        qt = self.querytrace.begin("overview")
        try:
            qkey = ",".join(f"{q:.6g}" for q in qs)
            source_q, counts, est = self._mirror_read(
                f"overview:{qkey}",
                lambda: self.agg.sketch_overview(qs),
                staleness_ms,
            )
            return {
                "percentiles": self._quantile_rows(
                    qs, source_q, counts, service_name, span_name
                ),
                "cardinalities": self._cardinality_rows(est),
                "counters": self.ingest_counters(),
            }
        finally:
            self.querytrace.finish(qt)

    def ingest_counters(self) -> dict:
        from zipkin_tpu.obs.device import OBSERVATORY

        _dev_totals = OBSERVATORY.totals()
        # host counters: exact and wrap-free (device counters are u32)
        return {
            **self.agg.host_counters,
            # read-side ledger: hostTransfers / query counts ≈ 1 is the
            # one-transfer invariant, observable in production
            "hostTransfers": self.agg.read_stats["host_transfers"],
            "rolledOnlyReads": self.agg.read_stats["rolled_only_reads"],
            "ctxReads": self.agg.read_stats["ctx_reads"],
            # process-wide transfer volume through the readpack
            # chokepoint, next to the per-store transfer count above
            "hostTransferBytes": readpack.transfer_bytes(),
            # device-program observatory aggregates (process-global):
            # steady state must hold deviceRecompiles at 0 after warmup
            "deviceProgramCalls": _dev_totals["calls"],
            "deviceCompiles": _dev_totals["compiles"],
            "deviceRecompiles": _dev_totals["recompiles"],
            # incremental link-ctx gauges (ISSUE 5): lanes the next
            # fresh read must delta-merge (bounded by rollup_segment),
            # ctx advances run, and the host wall of the last
            # ctx-advancing (rollup-fused) dispatch
            "ctxDeltaLanes": self.agg._lanes_since_rollup,
            "ctxAdvances": self.agg.ctx_stats["ctx_advances"],
            "ctxMaintenanceMs": self.agg.ctx_stats["ctx_maintenance_ms"],
            # HLL envelope guard: reads that saw a bias-dominated row /
            # rows beyond at the last read (both 0 in healthy operation)
            "hllEnvelopeExceeded": self._hll_envelope_exceeded,
            "hllBeyondEnvelopeRows": self._hll_beyond_envelope_rows,
            "serviceVocabOverflow": self.vocab.services.overflow,
            "keyVocabOverflow": self.vocab._overflow,
            # the fast path interns in C; rejected entries never reach
            # the Python journal so the C counter is separate
            "nativeVocabOverflow": (
                self._nvocab.overflow if self._nvocab is not None else 0
            ),
            # boot-time restore gauges (restoreMs / walReplayBatches /
            # walReplayMs): how much recovery cost the last boot
            **self.restore_stats,
            **(self._disk.counters() if self._disk is not None else {}),
            # at-rest integrity gauges (scrubBytes / segmentsQuarantined
            # / spansQuarantined / ...): what the background scrubber
            # verified and what it had to pull from service
            **(self.scrubber.counters() if self.scrubber is not None else {}),
            # sampling-tier gauges (samplerPublishes / samplerPressure /
            # budgetUtilization / samplerRate*) — sampledKept/Dropped
            # come exact from agg.host_counters above
            **(
                self.sampling_controller.counters()
                if self.sampling_controller is not None
                else {}
            ),
            # fan-out tier gauges (mpWorkersAlive / mpInflight /
            # mpRejected ...): present only when the MP tier is attached
            **(
                self.mp_ingester.stats() if self.mp_ingester is not None else {}
            ),
            # accuracy-observatory gauges (accuracyDigestP99RelErr /
            # accuracyHllRelErr / accuracyLinkRecall / shadow* ...):
            # present only when the shadow plane is attached
            **(
                self.accuracy.export_counters()
                if self.accuracy is not None
                else {}
            ),
            # query-plane observatory (obs/querytrace.py): stitched
            # per-query aggregates + the aggregator-lock contention
            # ledger (queryLock* gauges; the nested queryLock table is
            # skipped by flat consumers, rendered by /prometheus)
            **self.querytrace.counters(),
            # cached-read staleness: age-at-serve of the last cache hit
            # (read cache or bounded-stale deps cache), its high-water,
            # and the live read-cache entry count
            "readCacheServeAgeMs": round(self._read_cache_age_ms, 3),
            "readCacheServeAgeMaxMs": round(self._read_cache_age_max_ms, 3),
            "readCacheEntries": len(self._read_cache),
            # brownout cache-first/cache-only serves (ISSUE 13):
            # version-stale answers served under overload read modes
            "readCacheStaleServes": self._read_cache_stale_serves,
            # epoch-published read mirror (ISSUE 14): generation,
            # publish cost, lock-free serve tallies, staleness-at-serve
            # gauges — mirrorServeAgeMs backs the query_mirror_staleness
            # SLO and the zipkin_tpu_mirror_* prometheus families
            **self.mirror.counters(),
            # scale-out serving segment (serving/, ISSUE 19): publish /
            # overflow / demand-backchannel tallies plus the worst live
            # reader's age-at-serve (readerServeAgeMs — backs the
            # reader_staleness SLO) and generation lag
            "mirrorSegmentSinkErrors": self.mirror.segment_sink_errors,
            "readerDemandUnparsed": self._demand_unparsed,
            **(
                self._segment_publisher.counters()
                if self._segment_publisher is not None
                else {}
            ),
            # time-disaggregated sketch tier (ttSeals / ttSegments* /
            # ttWindowReads / ttMissingEpochs ...): seal cadence, ring
            # occupancy, and windowed-read merge cost
            **(
                self.timetier.export_counters()
                if self.timetier is not None
                else {}
            ),
        }

    def set_query_observatory(self, on: bool) -> None:
        """Enable/disable per-query tracing and the lock ledger together
        (server config plumb-through). Remembered so :meth:`clear`'s
        aggregator swap — which builds a fresh instrumented lock with
        the env default — reapplies the configured state."""
        self._query_obs_enabled = bool(on)
        self.querytrace.enabled = bool(on)
        lk = getattr(self.agg, "lock", None)
        if lk is not None and hasattr(lk, "set_enabled"):
            lk.set_enabled(on)

    def sampler_rates(self) -> dict:
        """{service: keep fraction} from the published rate table — the
        perServiceRate gauge surface (labels, so not in the flat
        ingest_counters dict). Empty when the sampling tier is off."""
        sampler = self.agg.sampler
        if sampler is None:
            return {}
        from zipkin_tpu.sampling import RATE_ONE

        out = {}
        for name in self.vocab.services.names:
            sid = self.vocab.services.get(name)
            if sid:
                out[name] = float(sampler.rate[sid]) / RATE_ONE
        return out

    # -- lifecycle -------------------------------------------------------

    def check(self) -> CheckResult:
        try:
            # zt-lint: disable=ZT06 — the health check's contract is to
            # prove the device round-trips; blocking IS the probe
            self.agg.block_until_ready()
            return CheckResult.OK
        except Exception as e:  # pragma: no cover - device failure path
            return CheckResult.failed(e)

    def close(self) -> None:
        self._closed = True
        if self.scrubber is not None:
            self.scrubber.stop()
        if self.sampling_controller is not None:
            self.sampling_controller.stop()
        if self._disk is not None:
            self._disk.close()
        self._archive.close()

    def clear(self) -> None:
        """Test helper: drop archive + reset device state."""
        from zipkin_tpu.parallel.sharded import ShardedAggregator

        self._archive.clear()
        self.agg = ShardedAggregator(self.config, mesh=self.agg.mesh)
        # sealed segments were cut from the old aggregator's buckets —
        # a windowed read must not merge them with the new one's
        if self.timetier is not None:
            self.timetier.clear()
        # the swap replaced the aggregator: the published mirror epoch
        # was cut against versions that no longer compare — drop it
        # (demand keys survive; the next publish refills)
        self.mirror.reset()
        # the swap replaced the instrumented lock; drop stitched state
        # from the old aggregator and reapply configured enablement
        self.querytrace.reset()
        if self._query_obs_enabled is not None:
            self.set_query_observatory(self._query_obs_enabled)
