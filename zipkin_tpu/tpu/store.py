"""TpuStorage: the StorageComponent backed by the device aggregation tier.

This is the rebuild's ``zipkin-storage-tpu`` module (BASELINE north
star): it implements the exact SPI of SURVEY.md §2.3 — so the collectors
and server use it interchangeably with the in-memory oracle — while
serving the aggregate read paths (dependencies, latency percentiles,
cardinalities) straight from device sketches.

Division of labor (hybrid by design, SURVEY.md §1 "TPU-rebuild mapping"):

- **Device** (per shard, merged over ICI on read): latency histograms +
  t-digests per (service, spanName), HLL trace cardinality per service,
  dependency-link matrices over the retained span ring.
- **Host archive**: a bounded `InMemoryStorage` keeps raw spans for exact
  trace reads and search (`getTraces`) — the role the reference delegates
  to row storage; beyond its eviction horizon, aggregates remain
  queryable from the device (which is the point of the sketch tier).

Idempotence: at-least-once transports can redeliver (SURVEY.md §3.3). The
archive dedups by (traceId, spanId, ...); device sketches accept bounded
double-count — the documented trade, testable against the oracle.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

from zipkin_tpu.internal.dates import epoch_minutes
from zipkin_tpu.model.span import DependencyLink, Span
from zipkin_tpu.ops import histogram as hist_ops
from zipkin_tpu.ops import hll as hll_ops
from zipkin_tpu.ops import tdigest as tdigest_ops
from zipkin_tpu.storage.memory import InMemoryStorage
from zipkin_tpu.storage.spi import (
    AutocompleteTags,
    QueryRequest,
    ServiceAndSpanNames,
    SpanConsumer,
    SpanStore,
    StorageComponent,
)
from zipkin_tpu.tpu.columnar import SpanColumns, Vocab, pack_spans
from zipkin_tpu.tpu.state import AggConfig
from zipkin_tpu.utils.call import Call
from zipkin_tpu.utils.component import CheckResult, Component


_PARSED_FIELDS = (
    "tl0", "tl1", "th0", "th1", "s0", "s1", "p0", "p1",
    "shared", "kind", "err", "has_dur", "ts_us", "dur_us",
    "debug", "svc_off", "svc_len", "rsvc_off", "rsvc_len",
    "name_off", "name_len", "svc_id", "rsvc_id", "name_id", "key_id",
)


class TpuStorage(
    StorageComponent, SpanConsumer, SpanStore, ServiceAndSpanNames, AutocompleteTags
):
    def __init__(
        self,
        *,
        config: Optional[AggConfig] = None,
        mesh=None,
        strict_trace_id: bool = True,
        search_enabled: bool = True,
        autocomplete_keys: Sequence[str] = (),
        archive_max_span_count: int = 500_000,
        pad_to_multiple: int = 1024,
    ) -> None:
        from zipkin_tpu.parallel.sharded import ShardedAggregator

        self.config = config or AggConfig()
        self.strict_trace_id = strict_trace_id
        self.search_enabled = search_enabled
        self.autocomplete_keys = tuple(autocomplete_keys)
        self.vocab = Vocab(
            max_services=self.config.max_services, max_keys=self.config.max_keys
        )
        self.agg = ShardedAggregator(self.config, mesh=mesh)
        self._archive = InMemoryStorage(
            max_span_count=archive_max_span_count,
            strict_trace_id=strict_trace_id,
            search_enabled=search_enabled,
            autocomplete_keys=autocomplete_keys,
        )
        self._pad = pad_to_multiple
        # largest single device batch AFTER padding: bounded by the digest
        # pending buffer (dynamic_update_slice of a batch bigger than it
        # cannot trace), rounded DOWN to a pad multiple so a padded chunk
        # never exceeds the bound.
        # Dispatch on the tunneled PJRT backend carries a large fixed
        # latency, so bigger device batches win nearly linearly; the only
        # hard bound is the digest pending buffer (dynamic_update_slice of
        # a batch bigger than it cannot trace).
        bound = min(self.config.digest_buffer, self.config.rollup_segment, 65536)
        self.max_batch = (bound // pad_to_multiple) * pad_to_multiple
        if self.max_batch <= 0:
            raise ValueError(
                f"digest_buffer ({self.config.digest_buffer}) must be >= "
                f"pad_to_multiple ({pad_to_multiple})"
            )
        self._closed = False
        # interning id-space coherence: the C-side vocab (fast path) and
        # the Python vocab (object path) assign ids sequentially; any
        # operation that interns must hold this lock so the orders match.
        self._intern_lock = threading.RLock()
        self._nvocab = None

    # -- SPI factories ---------------------------------------------------

    def span_consumer(self) -> SpanConsumer:
        return self

    def span_store(self) -> SpanStore:
        return self

    def service_and_span_names(self) -> ServiceAndSpanNames:
        return self

    def autocomplete_tags(self) -> AutocompleteTags:
        return self._archive

    # -- write path ------------------------------------------------------

    def accept(self, spans: Sequence[Span]) -> Call[None]:
        def run() -> None:
            if not spans:
                return
            self._archive.accept(spans).execute()
            # chunk: a giant POST must not exceed the device batch bound
            # (state transitions serialize on the aggregator's own lock)
            for lo in range(0, len(spans), self.max_batch):
                with self._intern_lock:
                    cols = pack_spans(
                        spans[lo : lo + self.max_batch], self.vocab, self._pad
                    )
                self.agg.ingest(cols)

        return Call.of(run)

    def ingest_json_fast(self, data: bytes, sampler=None):
        """Line-rate ingest: raw JSON v2 bytes -> device aggregates via the
        native columnar parser, skipping Span objects AND the host archive
        (the aggregate tier is the product at this rate; raw-span retention
        at line rate is delegated, as in the reference, to row storage).

        Returns (accepted, sample_dropped), or None when the native path
        can't take this payload (caller falls back to the object path).
        """
        from zipkin_tpu import native
        from zipkin_tpu.tpu.columnar import pack_parsed

        if not native.available():
            return None
        with self._intern_lock:
            if self._nvocab is None:
                self._nvocab = native.NativeVocab(self.vocab)
            self._nvocab.ensure_synced()
            parsed = native.parse_spans(data, nvocab=self._nvocab)
            if parsed is None:
                return None
            self._nvocab.sync()
        n = parsed.n
        dropped = 0
        if sampler is not None and sampler.rate < 1.0 and n:
            lo = (parsed.tl1[:n].astype(np.uint64) << np.uint64(32)) | parsed.tl0[
                :n
            ].astype(np.uint64)
            signed = lo.view(np.int64)
            # numpy abs(INT64_MIN) overflows back to INT64_MIN (negative);
            # Java parity maps MIN_VALUE -> MAX_VALUE so it drops at <1.0.
            t = np.abs(signed)
            t = np.where(t == np.iinfo(np.int64).min, np.iinfo(np.int64).max, t)
            keep = (t <= sampler._boundary) | (parsed.debug[:n] != 0)
            dropped = int(n - keep.sum())
            if dropped:
                idx = np.nonzero(keep)[0]
                for field in _PARSED_FIELDS:
                    col = getattr(parsed, field, None)
                    if col is not None:
                        setattr(parsed, field, col[:n][idx])
                parsed.n = n = len(idx)
        if n == 0:
            return 0, dropped
        for lo_i in range(0, n, self.max_batch):
            hi_i = min(lo_i + self.max_batch, n)
            if lo_i == 0 and hi_i == n:
                sub = parsed
            else:
                sub = native.ParsedColumns()
                sub.data = parsed.data
                for f in _PARSED_FIELDS:
                    col = getattr(parsed, f, None)
                    setattr(sub, f, None if col is None else col[lo_i:hi_i])
                sub.n = hi_i - lo_i
            cols = pack_parsed(sub, self.vocab, self._pad)
            self.agg.ingest(cols)
        return n, dropped

    # -- raw trace reads: host archive -----------------------------------

    def get_trace(self, trace_id: str) -> Call[List[Span]]:
        return self._archive.get_trace(trace_id)

    def get_traces(self, trace_ids: Sequence[str]) -> Call[List[List[Span]]]:
        return self._archive.get_traces(trace_ids)

    def get_traces_query(self, request: QueryRequest) -> Call[List[List[Span]]]:
        return self._archive.get_traces_query(request)

    def get_service_names(self) -> Call[List[str]]:
        return self._archive.get_service_names()

    def get_remote_service_names(self, service_name: str) -> Call[List[str]]:
        return self._archive.get_remote_service_names(service_name)

    def get_span_names(self, service_name: str) -> Call[List[str]]:
        return self._archive.get_span_names(service_name)

    def get_keys(self) -> Call[List[str]]:
        return self._archive.get_keys()

    def get_values(self, key: str) -> Call[List[str]]:
        return self._archive.get_values(key)

    # -- aggregate reads: device ----------------------------------------

    def get_dependencies(self, end_ts: int, lookback: int) -> Call[List[DependencyLink]]:
        def run() -> List[DependencyLink]:
            lo_min = epoch_minutes(end_ts - lookback)
            hi_min = epoch_minutes(end_ts)
            calls, errors = self.agg.dependency_matrices(lo_min, hi_min)
            out: List[DependencyLink] = []
            for p, c in zip(*np.nonzero(calls)):
                parent = self.vocab.services.lookup(int(p))
                child = self.vocab.services.lookup(int(c))
                if not parent or not child:
                    continue
                out.append(
                    DependencyLink(
                        parent=parent,
                        child=child,
                        call_count=int(calls[p, c]),
                        error_count=int(errors[p, c]),
                    )
                )
            return out

        return Call.of(run)

    def latency_quantiles(
        self,
        qs: Sequence[float],
        service_name: Optional[str] = None,
        span_name: Optional[str] = None,
        use_digest: bool = True,
        end_ts: Optional[int] = None,
        lookback: Optional[int] = None,
    ) -> List[dict]:
        """Latency percentile rows per (service, spanName) — the read the
        Lens duration-percentile context needs, served from sketches.

        With ``end_ts``/``lookback`` (epoch ms, as in the query API) the
        rows come from the time-sliced histograms — windowed percentiles,
        covering the most recent T*slice_minutes of traffic (older
        windows return no rows; the all-time path has no window).
        Returns dicts: {service, spanName, count, quantiles: {q: µs}}.
        """
        import jax.numpy as jnp

        qarr = jnp.asarray(np.asarray(qs, np.float32))
        if end_ts is None and lookback is not None:
            # Zipkin query convention: endTs defaults to "now" when only
            # lookback is given (QueryRequest semantics, SURVEY.md §2.3)
            end_ts = int(time.time() * 1000)
        if end_ts is not None:
            lb = lookback if lookback is not None else end_ts
            lo_min = epoch_minutes(end_ts - lb)
            hi_min = epoch_minutes(end_ts)
            merged_hist = self.agg.windowed_histograms(lo_min, hi_min)
            source_q = np.asarray(hist_ops.quantile(jnp.asarray(merged_hist), qarr))
        else:
            merged_hist, _, _ = self.agg.merged_sketches()
            if use_digest:
                digest = self.agg.merged_digest()
                source_q = np.asarray(tdigest_ops.quantile(digest, qarr))
            else:
                source_q = np.asarray(
                    hist_ops.quantile(jnp.asarray(merged_hist), qarr)
                )
        counts = np.asarray(hist_ops.total_count(jnp.asarray(merged_hist)))

        want_svc = (
            self.vocab.services.get(service_name.lower()) if service_name else None
        )
        if service_name and want_svc is None:
            return []
        out = []
        for kid in range(1, self.vocab.num_keys):
            svc_id, name_id = self.vocab.key_pair(kid)
            if want_svc is not None and svc_id != want_svc:
                continue
            name = self.vocab.span_names.lookup(name_id)
            if span_name and name != span_name.lower():
                continue
            if counts[kid] == 0:
                continue
            out.append(
                {
                    "serviceName": self.vocab.services.lookup(svc_id),
                    "spanName": name,
                    "count": int(counts[kid]),
                    "quantiles": {
                        float(q): float(source_q[kid, i]) for i, q in enumerate(qs)
                    },
                }
            )
        return out

    def trace_cardinalities(self) -> dict:
        """Estimated distinct trace counts: {"_global": n, service: n, ...}."""
        import jax.numpy as jnp

        _, hll_regs, _ = self.agg.merged_sketches()
        est = np.asarray(hll_ops.estimate(jnp.asarray(hll_regs)))
        out = {"_global": float(est[self.config.global_hll_row])}
        for name in self.vocab.services.names:
            sid = self.vocab.services.get(name)
            if sid:
                out[name] = float(est[sid])
        return out

    def ingest_counters(self) -> dict:
        # host counters: exact and wrap-free (device counters are u32)
        return {
            **self.agg.host_counters,
            "serviceVocabOverflow": self.vocab.services.overflow,
            "keyVocabOverflow": self.vocab._overflow,
        }

    # -- lifecycle -------------------------------------------------------

    def check(self) -> CheckResult:
        try:
            self.agg.block_until_ready()
            return CheckResult.OK
        except Exception as e:  # pragma: no cover - device failure path
            return CheckResult.failed(e)

    def close(self) -> None:
        self._closed = True
        self._archive.close()

    def clear(self) -> None:
        """Test helper: drop archive + reset device state."""
        from zipkin_tpu.parallel.sharded import ShardedAggregator

        self._archive.clear()
        self.agg = ShardedAggregator(self.config, mesh=self.agg.mesh)
