"""Time-disaggregated sketch tier: sealed time-bucket segments.

The device keeps a FAT current-bucket update sketch (the tb_* AggState
leaves: per-key t-digest clusters, HLL registers, link-edge planes — an
epoch ring of ``time_buckets`` slots x ``time_bucket_minutes`` each,
updated at line rate by the ingest step). This module is the other half
of the SF-sketch two-stage split: a ticker-driven **bucket seal** reads
one finished bucket off the device (``ShardedAggregator.tt_read`` with
lo==hi — one packed transfer) and freezes it into a compact, mergeable,
host-side **segment**. Windowed ``[lookback, endTs]`` queries then
select the covering run of segments and merge them in pure numpy
(ops/ttmerge.py) — digest recluster, HLL register-max, edge sums — with
at most ONE device pull for the unsealed current bucket.

Memory stays fixed the way obs/windows.py keeps its two tiers fixed:
a FINE ring of the most recent sealed buckets, coalescing into a COARSE
ring of pre-merged blocks of ``coarse_factor`` buckets each (a 24 h
lookback folds ~dozens of coarse blocks + a few fine edges, not
hundreds of fine buckets). Aged-out fine segments stay reachable on
disk.

Durability mirrors the PR 7 snapshot protocol: a segment is one
``tt-<epoch>.npz`` (fsync + atomic rename) plus a crc32-per-array
manifest sidecar committed after it; restore verifies the manifest and
QUARANTINES (renames aside, never unlinks) a rotted segment, serving
the window with a coverage gap instead of garbage. The seal path
carries the ``timetier.seal.pre_commit`` / ``post_commit`` crashpoints
and the ``timetier.segment`` corrupt site (zipkin_tpu.faults); the
device current-bucket leaves ride snapshot/WAL like every other leaf,
so a crash-resume reseals pending buckets from bit-identical state.

Staleness contract: bucket epoch ``e`` is sealable once ingest has
seen epoch ``e+1`` (``tt_max_epoch``); the newest epoch is always the
UNSEALED current bucket and is served straight off the device. A
window's sealed prefix never changes after seal — which is what makes
the demand-registered mirror keys (store.py ``ttq:`` keys) cacheable.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

import numpy as np

from zipkin_tpu import faults
from zipkin_tpu.ops import ttmerge

logger = logging.getLogger(__name__)

SEGMENT_VERSION = 1
_SEG_PREFIX = "tt-"
QUARANTINE_SUFFIX = ".quarantine"
# segment npz member order — the manifest records one crc per member
_MEMBERS = ("digest", "hll", "calls", "errs")


@dataclasses.dataclass(frozen=True)
class Segment:
    """One sealed bucket (``lo_ep == hi_ep``) or a coalesced coarse
    block (``[lo_ep, hi_ep]`` inclusive). Arrays are the mergeable
    compact forms the device read produced: digest [K, Cw, 2] f32,
    hll [S+1, m] u8, calls/errs [S, S] u32."""

    lo_ep: int
    hi_ep: int
    digest: np.ndarray
    hll: np.ndarray
    calls: np.ndarray
    errs: np.ndarray


@dataclasses.dataclass
class WindowAnswer:
    """One merged windowed read: the requested epoch range, the epochs
    actually covered (sealed segments + unsealed device read), and the
    merged sketches. ``missing`` counts requested epochs with no data
    (older than tier retention, or quarantined)."""

    lo_ep: int
    hi_ep: int
    covered: int
    missing: int
    unsealed: bool
    digest: np.ndarray
    hll: np.ndarray
    calls: np.ndarray
    errs: np.ndarray


def _fsync_dir(directory: str) -> None:
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


class TimeTier:
    """Host ring of sealed time-bucket segments + the seal protocol.

    Thread model: the sealer runs on the obs ticker thread; windowed
    reads run on server threads and at mirror-publish time. One plain
    RLock guards the rings and counters — hold times are small host
    folds (the aggregator lock is NOT taken under it; ``window`` takes
    the agg lock only through ``agg.tt_read`` for the unsealed tail)."""

    def __init__(
        self,
        config,
        directory: Optional[str] = None,
        fine_slots: int = 64,
        coarse_factor: int = 12,
        coarse_slots: int = 64,
        disk_cache_slots: int = 32,
    ) -> None:
        self.config = config
        self.granularity = int(config.time_bucket_minutes)
        self.directory = directory
        self.fine_slots = int(fine_slots)
        self.coarse_factor = int(coarse_factor)
        self.coarse_slots = int(coarse_slots)
        self._lock = threading.RLock()
        # fine ring: most recent sealed buckets, epoch-keyed
        self._fine: "OrderedDict[int, Segment]" = OrderedDict()
        # buckets evicted from fine, waiting to coalesce into one block
        self._pending_coarse: List[Segment] = []
        # coarse ring: pre-merged blocks, oldest first
        self._coarse: "deque[Segment]" = deque(maxlen=self.coarse_slots)
        # LRU of segments re-loaded from disk for old windows
        self._disk_cache: "OrderedDict[int, Segment]" = OrderedDict()
        self._disk_cache_slots = int(disk_cache_slots)
        self._disk_epochs: set = set()
        self.sealed_through = -1
        self.counters: Dict[str, float] = {
            "ttSeals": 0,
            "ttSealWallMsLast": 0.0,
            "ttSegmentsFine": 0,
            "ttSegmentsCoarse": 0,
            "ttSegmentsDisk": 0,
            "ttSegmentsQuarantined": 0,
            "ttDiskLoads": 0,
            "ttWindowReads": 0,
            "ttWindowMergeMsLast": 0.0,
            "ttMissingEpochs": 0,
        }
        if directory:
            os.makedirs(directory, exist_ok=True)
            self._boot_scan()

    # -- boot ------------------------------------------------------------

    def _boot_scan(self) -> None:
        """Adopt committed segments from a previous run: the on-disk
        epoch set is the restore source of truth (a post_commit crash
        left the segment durable before sealed_through advanced — it
        must be adopted, not resealed). Stray tmp files from a
        pre_commit crash are dead weight."""
        with self._lock:
            self._boot_scan_locked()

    def _boot_scan_locked(self) -> None:  # zt-lint: disable=ZT04 — _boot_scan holds self._lock
        for name in os.listdir(self.directory):
            path = os.path.join(self.directory, name)
            if name.endswith(".tmp"):
                try:
                    os.unlink(path)
                except OSError:
                    pass
                continue
            if not (name.startswith(_SEG_PREFIX) and name.endswith(".npz")):
                continue
            try:
                epoch = int(name[len(_SEG_PREFIX):-4])
            except ValueError:
                continue
            self._disk_epochs.add(epoch)
        if self._disk_epochs:
            self.sealed_through = max(self._disk_epochs)
        self.counters["ttSegmentsDisk"] = len(self._disk_epochs)

    # -- seal protocol ---------------------------------------------------

    def seal_due(self, agg) -> int:
        """Epochs ready to seal: everything strictly below the newest
        epoch ingest has touched (the unsealed current bucket), clamped
        to device-ring residency exactly like ``seal_up_to`` — epochs
        the W-slot ring has recycled are gaps, not due work."""
        top = agg.tt_max_epoch
        if top < 0:
            return 0
        lo = max(
            self.sealed_through + 1,
            top - (int(self.config.time_buckets) - 1),
        )
        return max(0, top - lo)

    def seal_up_to(self, agg, limit: Optional[int] = None) -> int:
        """Seal every due epoch (oldest first). Epochs the device ring
        has already recycled past seal as EMPTY segments — retention
        ran out before the sealer caught up; the gap is recorded, not
        invented. Returns segments sealed."""
        top = agg.tt_max_epoch
        if top < 0:
            return 0
        lo = self.sealed_through + 1
        # never backfill past device residency: an epoch the W-slot ring
        # has recycled would seal as an EMPTY segment — skip it instead
        # (cover() reports the gap as missing), which also bounds a
        # post-downtime catch-up to at most W-1 seals
        lo = max(lo, top - (int(self.config.time_buckets) - 1))
        sealed = 0
        for epoch in range(lo, top):
            self._seal_one(agg, epoch)
            sealed += 1
            if limit is not None and sealed >= limit:
                break
        return sealed

    def _seal_one(self, agg, epoch: int) -> None:
        """Freeze bucket ``epoch`` into a segment: one device read
        (tt_read flushes pending digest points first — the ttflush WAL
        marker keeps that replay-exact), atomic persist, then admit to
        the fine ring. Idempotent by epoch-named file: resealing after
        a post_commit crash adopts the committed file."""
        t0 = time.perf_counter()
        ep, regs, digest, calls, errs = agg.tt_read(epoch, epoch)
        seg = Segment(
            lo_ep=epoch, hi_ep=epoch,
            digest=np.asarray(digest, np.float32),
            hll=np.asarray(regs, np.uint8),
            calls=np.asarray(calls, np.uint32),
            errs=np.asarray(errs, np.uint32),
        )
        with self._lock:
            if self.directory:
                self._persist(seg)
            faults.crashpoint("timetier.seal.post_commit")
            self._admit(seg)
            self.sealed_through = max(self.sealed_through, epoch)
            self.counters["ttSeals"] += 1
            self.counters["ttSealWallMsLast"] = (
                time.perf_counter() - t0
            ) * 1000.0

    def _seg_name(self, epoch: int) -> str:
        return f"{_SEG_PREFIX}{epoch:012d}.npz"

    def _persist(self, seg: Segment) -> None:  # zt-lint: disable=ZT04 — caller holds self._lock
        """Commit one segment: npz tmp + fsync, crashpoint, atomic
        rename, dir fsync, then the crc manifest sidecar (same commit
        shape as snapshot generations — the sidecar is the integrity
        record, the npz rename is the existence commit)."""
        arrays = {
            "digest": seg.digest, "hll": seg.hll,
            "calls": seg.calls, "errs": seg.errs,
        }
        name = self._seg_name(seg.lo_ep)
        fd, tmp = tempfile.mkstemp(dir=self.directory, suffix=".npz.tmp")
        with os.fdopen(fd, "wb") as f:
            np.savez_compressed(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        faults.crashpoint("timetier.seal.pre_commit")
        path = os.path.join(self.directory, name)
        os.replace(tmp, path)
        _fsync_dir(self.directory)
        meta = {
            "version": SEGMENT_VERSION,
            "epoch": seg.lo_ep,
            "granularity_minutes": self.granularity,
            "digest": "crc32",
            "member_crcs": {
                m: zlib.crc32(np.ascontiguousarray(arrays[m]).tobytes())
                for m in _MEMBERS
            },
        }
        mfd, mtmp = tempfile.mkstemp(dir=self.directory, suffix=".json.tmp")
        with os.fdopen(mfd, "w") as f:
            f.write(json.dumps(meta))
            f.flush()
            os.fsync(f.fileno())
        os.replace(mtmp, path[:-4] + ".meta.json")
        _fsync_dir(self.directory)
        # bit-rot injection: damage the just-committed segment at rest
        # so the load-time manifest check + quarantine path is soak-
        # tested (the ZT_CORRUPT family, tests/test_timetier.py)
        faults.corrupt_point(
            "timetier.segment", path, 0, os.path.getsize(path)
        )
        self._disk_epochs.add(seg.lo_ep)
        self.counters["ttSegmentsDisk"] = len(self._disk_epochs)

    def _admit(self, seg: Segment) -> None:  # zt-lint: disable=ZT04 — caller holds self._lock
        """Fine ring admit + fixed-memory coalesce (callers hold lock)."""
        self._fine[seg.lo_ep] = seg
        self._fine.move_to_end(seg.lo_ep)
        while len(self._fine) > self.fine_slots:
            _, old = self._fine.popitem(last=False)
            self._pending_coarse.append(old)
            if len(self._pending_coarse) >= self.coarse_factor:
                self._coarse.append(self._coalesce(self._pending_coarse))
                self._pending_coarse = []
        self.counters["ttSegmentsFine"] = len(self._fine)
        self.counters["ttSegmentsCoarse"] = len(self._coarse)

    def _coalesce(self, segs: List[Segment]) -> Segment:
        """Pre-merge a run of fine segments into one coarse block —
        the fold a 24 h window would otherwise redo per query."""
        segs = sorted(segs, key=lambda s: s.lo_ep)
        return Segment(
            lo_ep=segs[0].lo_ep, hi_ep=segs[-1].hi_ep,
            digest=ttmerge.merge_digests([s.digest for s in segs]),
            hll=ttmerge.merge_hll([s.hll for s in segs]),
            calls=ttmerge.merge_edges(
                [s.calls for s in segs]
            ).astype(np.uint32),
            errs=ttmerge.merge_edges(
                [s.errs for s in segs]
            ).astype(np.uint32),
        )

    # -- disk load -------------------------------------------------------

    def _load_disk(self, epoch: int) -> Optional[Segment]:  # zt-lint: disable=ZT04 — caller holds self._lock
        """Load + verify one on-disk segment (callers hold lock). A
        manifest mismatch or unreadable npz quarantines the pair and
        reports the epoch missing — a flipped bit must cost coverage,
        never a silently-wrong percentile."""
        if epoch in self._disk_cache:
            self._disk_cache.move_to_end(epoch)
            return self._disk_cache[epoch]
        if epoch not in self._disk_epochs:
            return None
        path = os.path.join(self.directory, self._seg_name(epoch))
        meta_path = path[:-4] + ".meta.json"
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            loaded = np.load(path)
            arrays = {m: loaded[m] for m in _MEMBERS}
        except Exception as e:
            logger.warning(
                "time-tier segment %s unreadable (%s); quarantining",
                path, e,
            )
            self._quarantine(epoch)
            return None
        crcs = meta.get("member_crcs", {})
        for m in _MEMBERS:
            got = zlib.crc32(np.ascontiguousarray(arrays[m]).tobytes())
            if int(crcs.get(m, -1)) != got:
                logger.warning(
                    "time-tier segment %s: member %s crc mismatch "
                    "(%08x != manifest %s) — bit rot; quarantining",
                    path, m, got, crcs.get(m),
                )
                self._quarantine(epoch)
                return None
        seg = Segment(
            lo_ep=epoch, hi_ep=epoch,
            digest=arrays["digest"], hll=arrays["hll"],
            calls=arrays["calls"], errs=arrays["errs"],
        )
        self._disk_cache[epoch] = seg
        self._disk_cache.move_to_end(epoch)
        while len(self._disk_cache) > self._disk_cache_slots:
            self._disk_cache.popitem(last=False)
        self.counters["ttDiskLoads"] += 1
        return seg

    def _quarantine(self, epoch: int) -> None:  # zt-lint: disable=ZT04 — caller holds self._lock
        path = os.path.join(self.directory, self._seg_name(epoch))
        for victim in (path, path[:-4] + ".meta.json"):
            try:
                # zt-lint: disable=ZT12 — quarantine moves already-corrupt bytes ASIDE; the poison file's durability is not a recovery invariant (a lost rename just re-quarantines next boot)
                os.replace(victim, victim + QUARANTINE_SUFFIX)
            except OSError:
                pass
        self._disk_epochs.discard(epoch)
        self._disk_cache.pop(epoch, None)
        self.counters["ttSegmentsQuarantined"] += 1
        self.counters["ttSegmentsDisk"] = len(self._disk_epochs)

    # -- query side ------------------------------------------------------

    def cover(
        self, lo_ep: int, hi_ep: int
    ) -> Tuple[List[Segment], int, int]:
        """(segments, covered, missing) for the SEALED epochs of
        ``[lo_ep, hi_ep]``: coarse blocks where one fits entirely inside
        the range, fine/memory segments next, disk loads last. Epochs
        with no surviving segment count as missing."""
        hi = min(hi_ep, self.sealed_through)
        parts: List[Segment] = []
        covered = 0
        missing = 0
        with self._lock:
            # everything below the tier's oldest reachable epoch is
            # missing by arithmetic — a multi-year lookback must not
            # turn into a per-epoch scan of epochs nothing retains
            floor = self.sealed_through + 1
            if self._disk_epochs:
                floor = min(floor, min(self._disk_epochs))
            if self._fine:
                floor = min(floor, next(iter(self._fine)))
            if self._coarse:
                floor = min(floor, self._coarse[0].lo_ep)
            start = max(lo_ep, floor)
            if hi >= lo_ep:
                missing += max(0, min(start, hi + 1) - lo_ep)
            coarse_at = {b.lo_ep: b for b in self._coarse}
            e = start
            while e <= hi:
                block = coarse_at.get(e)
                if block is not None and block.hi_ep <= hi:
                    parts.append(block)
                    covered += block.hi_ep - block.lo_ep + 1
                    e = block.hi_ep + 1
                    continue
                seg = self._fine.get(e)
                if seg is None:
                    # epochs inside a PARTIALLY-overlapping coarse block
                    # land here too: the pre-merged block folded epochs
                    # outside the range, so exactness requires the fine
                    # segment — disk retains every sealed fine bucket
                    seg = self._load_disk(e) if self.directory else None
                if seg is not None:
                    parts.append(seg)
                    covered += 1
                else:
                    missing += 1
                e += 1
        return parts, covered, missing

    def window(self, agg, lo_ep: int, hi_ep: int) -> WindowAnswer:
        """The merged windowed read: sealed segments folded host-side
        (ops/ttmerge.py) + one device read for the unsealed suffix when
        the range reaches past ``sealed_through``. This function is the
        compute behind the mirror's demand-registered ``ttq:`` keys —
        a sealed-only window never touches the aggregator lock."""
        t0 = time.perf_counter()
        parts, covered, missing = self.cover(lo_ep, hi_ep)
        unsealed = hi_ep > self.sealed_through
        if unsealed:
            u_lo = max(lo_ep, self.sealed_through + 1)
            ep, regs, digest, calls, errs = agg.tt_read(u_lo, hi_ep)
            parts = parts + [Segment(
                lo_ep=u_lo, hi_ep=hi_ep,
                digest=np.asarray(digest, np.float32),
                hll=np.asarray(regs, np.uint8),
                calls=np.asarray(calls, np.uint32),
                errs=np.asarray(errs, np.uint32),
            )]
            present = set(int(x) for x in np.asarray(ep) if x >= 0)
            covered += len(
                [e for e in present if u_lo <= e <= hi_ep]
            )
        if parts:
            digest = ttmerge.merge_digests([p.digest for p in parts])
            hll = ttmerge.merge_hll([p.hll for p in parts])
            calls = ttmerge.merge_edges([p.calls for p in parts])
            errs = ttmerge.merge_edges([p.errs for p in parts])
        else:
            cfg = self.config
            k = int(cfg.max_keys)
            cw = int(cfg.time_digest_centroids)
            s = int(cfg.max_services)
            digest = np.zeros((k, cw, 2), np.float32)
            hll = np.zeros(
                (int(cfg.hll_rows), 1 << int(cfg.hll_precision)), np.uint8
            )
            calls = np.zeros((s, s), np.uint64)
            errs = np.zeros((s, s), np.uint64)
        with self._lock:
            self.counters["ttWindowReads"] += 1
            self.counters["ttWindowMergeMsLast"] = (
                time.perf_counter() - t0
            ) * 1000.0
            self.counters["ttMissingEpochs"] += missing
        return WindowAnswer(
            lo_ep=lo_ep, hi_ep=hi_ep, covered=covered, missing=missing,
            unsealed=unsealed, digest=digest, hll=hll,
            calls=calls, errs=errs,
        )

    # -- maintenance -----------------------------------------------------

    def clear(self) -> None:
        """Forget every segment (store.clear()): rings, caches, and the
        on-disk epoch index reset; disk files are left for postmortem
        (clear is a test/ops affordance, not retention)."""
        with self._lock:
            self._fine.clear()
            self._pending_coarse = []
            self._coarse.clear()
            self._disk_cache.clear()
            self._disk_epochs = set()
            self.sealed_through = -1
            self.counters["ttSegmentsFine"] = 0
            self.counters["ttSegmentsCoarse"] = 0
            self.counters["ttSegmentsDisk"] = 0

    def export_counters(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.counters)
