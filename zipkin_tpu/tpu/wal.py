"""Host write-ahead log of fused wire batches (VERDICT r2 order 6).

SURVEY.md §5's failure-detection row calls for a "host WAL of raw
batches so a device restart replays the window": snapshots
(tpu/snapshot.py) capture sketch state periodically, but HTTP/gRPC
ingest BETWEEN snapshots lives only in volatile HBM — the reference
never loses acked spans (durability is delegated to its storage
backends; Kafka resumes from offsets). This module closes that gap for
the device aggregates:

- every batch that reaches ``ShardedAggregator.ingest_fused`` is
  appended as one record: the packed ``[shards, 11, per]`` u32 wire
  image (already contiguous — the append is a straight write, no
  serialization) plus the GLOBAL vocab entries interned since the last
  record, so replay reconstructs the identical id space;
- records carry a monotone sequence number; snapshots store the last
  sequence folded into the captured state, and restore replays only
  ``seq > snapshot.wal_seq`` — exactly the batches the snapshot missed;
- a crc over the payload detects the torn tail record of a mid-write
  crash: replay stops cleanly at the last complete record;
- segments rotate by size and are deleted once a newer snapshot covers
  them.

The sampled raw-span archive is NOT logged: it is a bounded, lossy
cache by design (1-in-N traces, evicted by capacity), so replaying it
would fake a durability the tier never promised. Counter/link/sketch
parity after crash+replay is asserted in tests/test_wal.py.
"""

from __future__ import annotations

import contextlib
import errno
import json
import logging
import os
import struct
import time
import zlib
from typing import Callable, Iterator, Optional, Tuple

import numpy as np

from zipkin_tpu import faults, obs
from zipkin_tpu.obs import critpath

logger = logging.getLogger(__name__)

_MAGIC = 0x5A57414C  # "ZWAL"
_HEADER = struct.Struct("<IQII I")  # magic, seq, meta_len, payload_len, crc


class WriteAheadLog:
    def __init__(
        self,
        directory: str,
        max_segment_bytes: int = 256 * 1024 * 1024,
        fsync: bool = False,
    ) -> None:
        self.directory = directory
        self.max_segment_bytes = max_segment_bytes
        self.fsync = fsync
        os.makedirs(directory, exist_ok=True)
        self._fh = None
        self._path: Optional[str] = None
        self._fh_bytes = 0
        self._seg_idx = 0
        self._seq = 0
        self._closed = False
        self._batch_depth = 0  # >0 inside batched(): defer flush/fsync
        # disk-exhaustion degraded mode (ISSUE 13): an ENOSPC append
        # does NOT crash the ingest path — the record is missed, the log
        # flags itself at-risk (acked spans between here and the next
        # durable snapshot would not survive a crash), and the flag
        # stays sticky until a snapshot re-covers the full state
        # (storage/tpu.py calls clear_at_risk() after a committed save)
        self.at_risk = False
        self.enospc_count = 0
        self.missed_records = 0
        # resume numbering after the existing records — via a HEADER
        # walk, not records(): records() stops at the first bad payload
        # crc, so mid-segment rot would hide the seq high-water mark and
        # a revived writer would re-issue seqs a snapshot already covers
        # (replay silently skips covered seqs: acked-span loss)
        self._seq = self._scan_high_seq()
        segs = self._segments()
        if segs:
            self._seg_idx = segs[-1][0] + 1

    # -- write side ------------------------------------------------------

    def append(self, fused: np.ndarray, meta: dict) -> int:
        """Append one batch; returns its sequence number. ``meta`` must
        be JSON-serializable; shape/dtype are recorded automatically."""
        if self._closed:
            # without this, a hook captured by a racing ingest thread
            # before close() detached it would silently REOPEN the
            # segment via _file_for and log a batch after the final
            # snapshot — double-replay on next boot (r3 review finding)
            raise RuntimeError("WAL is closed")
        t0 = time.perf_counter()
        self._seq += 1
        # memoryview, not tobytes(): the image is already contiguous u32
        # (or made so here) and BufferedWriter/crc32 both consume the
        # buffer protocol, so the record costs zero payload copies
        # (cast() refuses views with a zero in the shape, so empty
        # images — flush markers — take the literal-bytes branch)
        arr = np.ascontiguousarray(fused, np.uint32)
        payload = arr.data.cast("B") if arr.size else memoryview(b"")
        meta = dict(meta, shape=list(fused.shape))
        meta_b = json.dumps(meta, separators=(",", ":")).encode()
        head = _HEADER.pack(
            _MAGIC, self._seq, len(meta_b), len(payload),
            zlib.crc32(payload),
        )
        rec_len = len(head) + len(meta_b) + len(payload)
        deferred = self._batch_depth > 0
        try:
            faults.resource_point("wal.append")
            fh = self._file_for(rec_len)
            # the record is written in two pieces so the mid-append
            # crashpoint sits at the worst tear: header+meta on disk,
            # payload missing — replay must detect the torn record and
            # stop at it
            fh.write(head + meta_b)
            if faults.is_armed("wal.append.mid"):
                fh.flush()  # the partial record must be kernel-visible
                # for the in-process (raise) crash action to leave the
                # same on-disk state a SIGKILL after a real flush would
            faults.crashpoint("wal.append.mid")
            fh.write(payload)
            if not deferred:
                fh.flush()
            faults.crashpoint("wal.append.pre_fsync")
            t1 = time.perf_counter()
            # the critical-path ledger wants append and fsync as
            # DISJOINT intervals (the recorder's wal_append stage keeps
            # including the fsync): a no-op unless a traced MP payload
            # is being flushed on this thread
            critpath.stamp_active(
                critpath.SEG_WAL_APPEND, int(t0 * 1e9), int(t1 * 1e9)
            )
            if self.fsync and not deferred:
                os.fsync(fh.fileno())
                t2 = time.perf_counter()
                obs.record("wal_fsync", t2 - t1)
                critpath.stamp_active(
                    critpath.SEG_WAL_FSYNC, int(t1 * 1e9), int(t2 * 1e9)
                )
        except OSError as e:
            if e.errno != errno.ENOSPC:
                raise
            self._note_enospc()
            return self._seq
        # bit-rot injection site (ISSUE 7): the record's payload bytes
        # are durable — damage them at rest; the process keeps running
        # (a deferred append must land on disk first for rot to have
        # bytes to chew on)
        if deferred and faults.is_corrupt_armed("wal.record"):
            fh.flush()
        faults.corrupt_point(
            "wal.record", self._path,
            self._fh_bytes + _HEADER.size + len(meta_b), len(payload),
        )
        self._fh_bytes += rec_len
        obs.record("wal_append", time.perf_counter() - t0)
        return self._seq

    @contextlib.contextmanager
    def batched(self):
        """Vectored append: records appended inside this context defer
        the per-record flush/fsync, and exiting commits the whole run
        with ONE flush (+ one fsync when enabled) — the span-ring
        dispatcher's multi-group flush pass amortizes its durability
        syscalls this way. Record FORMAT is untouched (each append still
        writes its own header/meta/payload/crc), so ``records()``/
        ``replay()`` cannot tell a batched run from serial appends; only
        the ack must wait for the commit, which the dispatcher does.
        ``wal.append.mid`` keeps its armed-flush semantics per record."""
        if self._closed:
            raise RuntimeError("WAL is closed")
        self._batch_depth += 1
        try:
            yield self
        finally:
            self._batch_depth -= 1
            if self._batch_depth == 0:
                self._commit_batch()

    def _commit_batch(self) -> None:
        fh = self._fh
        if fh is None:
            return
        t1 = time.perf_counter()
        try:
            fh.flush()
            if self.fsync:
                os.fsync(fh.fileno())
                t2 = time.perf_counter()
                obs.record("wal_fsync", t2 - t1)
                critpath.stamp_active(
                    critpath.SEG_WAL_FSYNC, int(t1 * 1e9), int(t2 * 1e9)
                )
        except OSError as e:
            if e.errno != errno.ENOSPC:
                raise
            self._note_enospc()

    def _note_enospc(self) -> None:
        """Disk full mid-append: the record is lost (it gets a seq but
        no durable bytes) and the segment may carry a torn tail. Rotate
        so post-recovery appends land in a FRESH segment — replay skips
        a torn segment's tail, so stacking good records behind the tear
        would silently lose them. The log keeps accepting appends (each
        retries the disk) and flags itself at-risk until a snapshot
        re-covers the missed window."""
        self.enospc_count += 1
        self.missed_records += 1
        if not self.at_risk:
            logger.error(
                "WAL append hit ENOSPC at seq %d: durability AT RISK "
                "(acked spans not crash-safe until the next snapshot "
                "commit)", self._seq,
            )
        self.at_risk = True
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None

    def clear_at_risk(self) -> None:
        """Called after a committed snapshot: full state is durable
        again, the ENOSPC-missed WAL window no longer matters."""
        if self.at_risk:
            logger.info(
                "WAL at-risk cleared: snapshot re-covered the missed "
                "window (%d records lost to ENOSPC)", self.missed_records,
            )
        self.at_risk = False

    def _file_for(self, rec_len: int):
        if self._fh is not None and (
            self._fh_bytes + rec_len > self.max_segment_bytes
        ):
            self._fh.close()
            self._fh = None
        if self._fh is None:
            path = os.path.join(
                self.directory, f"wal-{self._seg_idx:08d}.log"
            )
            self._seg_idx += 1
            self._fh = open(path, "ab")
            self._path = path
            self._fh_bytes = os.path.getsize(path)
        return self._fh

    def _scan_high_seq(self) -> int:
        """Max seq over every structurally valid record HEADER across
        all segments. Payload damage (flipped/zeroed bytes) leaves the
        headers after it reachable, so rot cannot roll numbering back;
        a rotted header still ends the walk early — attach() closes that
        residual gap by flooring the counter at the snapshot's seq."""
        top = 0
        for _, path in self._segments():
            try:
                with open(path, "rb") as fh:
                    while True:
                        head = fh.read(_HEADER.size)
                        if len(head) < _HEADER.size:
                            break
                        magic, seq, meta_len, payload_len, _ = _HEADER.unpack(
                            head
                        )
                        if magic != _MAGIC:
                            break
                        top = max(top, seq)
                        fh.seek(meta_len + payload_len, os.SEEK_CUR)
            except OSError:
                continue
        return top

    # -- read side -------------------------------------------------------

    def _segments(self):
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("wal-") and name.endswith(".log"):
                try:
                    out.append(
                        (int(name[4:-4]), os.path.join(self.directory, name))
                    )
                except ValueError:
                    continue
        out.sort()
        return out

    def records(
        self, from_seq: int = 0
    ) -> Iterator[Tuple[int, dict, np.ndarray]]:
        """Yield (seq, meta, fused) for every complete record with
        ``seq > from_seq``. A torn/corrupt record skips the REST OF ITS
        SEGMENT only — in the designed crash scenario the torn record is
        a segment's write tail, and LATER segments (appended by a
        post-crash process) hold independently-acked batches whose vocab
        deltas build on exactly the replay state at the tear, so they
        must still replay (a whole-log stop here silently dropped them)."""
        for _, path in self._segments():
            with open(path, "rb") as fh:
                while True:
                    rec_off = fh.tell()
                    head = fh.read(_HEADER.size)
                    if not head:
                        break
                    if len(head) < _HEADER.size:
                        logger.warning(
                            "WAL %s: torn header at offset %d; skipping "
                            "segment tail", path, rec_off,
                        )
                        break
                    magic, seq, meta_len, payload_len, crc = _HEADER.unpack(
                        head
                    )
                    if magic != _MAGIC:
                        logger.warning(
                            "WAL %s: bad magic at offset %d; skipping "
                            "segment tail", path, rec_off,
                        )
                        break
                    if seq <= from_seq:
                        # covered by the snapshot: seek past the body
                        # instead of reading + CRC-checking bytes the
                        # caller is about to discard — resume from a
                        # late snapshot used to decode the entire log
                        # it then skipped. A seek past EOF (covered torn
                        # tail) is benign: the next header read comes
                        # back empty and ends the segment.
                        fh.seek(meta_len + payload_len, os.SEEK_CUR)
                        continue
                    meta_b = fh.read(meta_len)
                    payload = fh.read(payload_len)
                    if len(meta_b) < meta_len or len(payload) < payload_len:
                        logger.warning(
                            "WAL %s: torn record seq %d at offset %d; "
                            "skipping segment tail", path, seq, rec_off,
                        )
                        break
                    if zlib.crc32(payload) != crc:
                        # seq + offset so a postmortem can tell exactly
                        # where the abandonment started and how much of
                        # the segment it cost (ISSUE 7 satellite)
                        logger.warning(
                            "WAL %s: bad crc on record seq %d at offset %d; "
                            "skipping segment tail", path, seq, rec_off,
                        )
                        break
                    meta = json.loads(meta_b)
                    fused = np.frombuffer(payload, np.uint32).reshape(
                        meta["shape"]
                    )
                    yield seq, meta, fused

    # -- maintenance -----------------------------------------------------

    def truncate_covered(self, covered_seq: int) -> None:
        """Delete segments whose every record is <= covered_seq (already
        folded into a durable snapshot)."""
        segs = self._segments()
        newest_idx = segs[-1][0] if segs else -1
        for idx, path in segs:
            if idx == newest_idx:
                # Never unlink the newest segment, even when fully
                # covered. It is the live segment when one is open, and
                # after a reopen-without-writes it is the only carrier
                # of the seq high-water mark: deleting it would make the
                # next boot's records() scan find nothing, restart
                # numbering at 1, and hand post-truncate appends seqs
                # <= the snapshot's wal_seq — which replay would then
                # silently skip (acked-span loss). The old guard
                # (`self._fh is not None and self._fh_bytes`) only
                # protected the segment while a writer had it open.
                continue
            max_seq = 0
            try:
                with open(path, "rb") as fh:
                    while True:
                        head = fh.read(_HEADER.size)
                        if len(head) < _HEADER.size:
                            break
                        magic, seq, meta_len, payload_len, _ = _HEADER.unpack(
                            head
                        )
                        if magic != _MAGIC:
                            break
                        max_seq = max(max_seq, seq)
                        fh.seek(meta_len + payload_len, os.SEEK_CUR)
            except OSError:
                continue
            if max_seq and max_seq <= covered_seq:
                os.unlink(path)
                logger.info("WAL segment %s truncated (<= %d)", path, covered_seq)

    def sealed_segment_paths(self):
        """Segment paths EXCLUDING the newest — the scrub set. The
        newest segment is the live writer target and the seq high-water
        carrier; it is never scrubbed-quarantined (runtime/scrub.py)."""
        return [path for _, path in self._segments()[:-1]]

    def close(self) -> None:
        self._closed = True
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def verify_segment(path: str) -> dict:
    """At-rest integrity scan of one segment (the scrubber's WAL leg):
    re-read every record, checking structure, payload crc, AND meta
    JSON validity (the header crc covers only the payload — rotted meta
    would otherwise surface as a json error mid-replay). Returns
    ``{"ok", "records", "max_seq", "bytes", "bad_seq", "bad_offset"}``;
    on damage, ``bad_seq``/``bad_offset`` locate the first bad record
    and ``max_seq`` covers only the records BEFORE it."""
    out = dict(
        ok=True, records=0, max_seq=0, bytes=0, bad_seq=None,
        bad_offset=None,
    )
    with open(path, "rb") as fh:
        while True:
            rec_off = fh.tell()
            head = fh.read(_HEADER.size)
            if not head:
                break
            bad = len(head) < _HEADER.size
            seq = None
            if not bad:
                magic, seq, meta_len, payload_len, crc = _HEADER.unpack(head)
                bad = magic != _MAGIC
            if not bad:
                meta_b = fh.read(meta_len)
                payload = fh.read(payload_len)
                bad = (
                    len(meta_b) < meta_len
                    or len(payload) < payload_len
                    or zlib.crc32(payload) != crc
                )
                if not bad:
                    try:
                        json.loads(meta_b)
                    except ValueError:
                        bad = True
            if bad:
                out["ok"] = False
                out["bad_seq"] = seq
                out["bad_offset"] = rec_off
                break
            out["records"] += 1
            out["max_seq"] = max(out["max_seq"], seq)
            out["bytes"] = fh.tell()
    return out


def attach(store, wal: WriteAheadLog) -> WriteAheadLog:
    """Wire a WAL into a TpuStorage: every ingest_fused batch is logged
    with the vocab delta since the previous record, and the aggregator
    records the applied sequence for snapshot coordination. Call AFTER
    any replay so the vocab delta cursors start at the current state."""
    vocab = store.vocab
    # numbering floor: never hand a new append a seq the restored
    # snapshot already covers (rotted headers can hide the true
    # high-water mark from the boot scan; covered seqs are skipped at
    # replay, so a re-issued one would lose an acked batch)
    wal._seq = max(wal._seq, int(getattr(store.agg, "wal_seq", 0)))
    sent = {"svc": 1, "name": 1, "pair": 1}
    # fast-forward the delta cursors past what a restored snapshot (or
    # prior replay) already covers — those entries are in snapshot meta
    sent["svc"] = len(vocab.services._names)
    sent["name"] = len(vocab.span_names._names)
    sent["pair"] = len(vocab._key_list)

    def hook(fused, n_spans, n_dur, n_err, ts_range, extra=None) -> int:
        with store._intern_lock:
            svc_new = vocab.services._names[sent["svc"]:]
            name_new = vocab.span_names._names[sent["name"]:]
            pairs_new = vocab._key_list[sent["pair"]:]
            sent["svc"] += len(svc_new)
            sent["name"] += len(name_new)
            sent["pair"] += len(pairs_new)
        meta = dict(
            n_spans=n_spans, n_dur=n_dur, n_err=n_err,
            ts_range=list(ts_range) if ts_range else None,
            svc=svc_new, names=name_new,
            pairs=[list(p) for p in pairs_new],
        )
        if extra:
            # sampling-tier sidecar meta: per-batch pre-compaction
            # seen/kept tallies, or a zero-lane "sctl" table-delta record
            # (controller publish) replay applies at this exact point of
            # the batch stream
            meta.update(extra)
        return wal.append(fused, meta)

    store.agg.wal_hook = hook
    store.wal = wal
    return wal


def replay(store, wal: WriteAheadLog, from_seq: int = 0) -> int:
    """Re-apply every WAL record after ``from_seq`` (the snapshot's
    cutoff) to the store: vocab deltas first (reconstructing the id
    space in the original intern order), then the fused batch. The WAL
    hook is suspended during replay. Returns batches applied."""
    agg = store.agg
    vocab = store.vocab
    hook, agg.wal_hook = getattr(agg, "wal_hook", None), None
    applied = 0
    try:
        for seq, meta, fused in wal.records(from_seq):
            with store._intern_lock:
                for s in meta.get("svc", []):
                    vocab.services.intern(s)
                for s in meta.get("names", []):
                    vocab.span_names.intern(s)
                for a, b in meta.get("pairs", []):
                    # position-faithful: the journal records the exact
                    # historical pair-id sequence (including any catch-
                    # all rows the writing build reserved) — re-deriving
                    # via key_id would shift every id when interning
                    # rules differ between builds (r4 review finding)
                    vocab.append_pair(a, b)
            sctl = meta.get("sctl")
            if sctl and hasattr(store, "apply_sctl"):
                # sampling-controller publish: apply the sparse table
                # delta to the host mirror HERE, between the same two
                # batches the live run published between — later replayed
                # verdicts must read the post-publish tables
                store.apply_sctl(sctl)
            if meta.get("ttflush"):
                # explicit digest flush marker (percentile reads, the
                # time-tier sealer): t-digest folding is order-sensitive,
                # so replay re-applies the flush at the exact stream
                # position — the time-bucket digests (tb_digest) come
                # back bit-identical only if pending points fold in the
                # same groups as the live run. wal_hook is None here, so
                # the replayed flush never re-logs its own marker.
                agg.flush_now()
            if meta.get("ttroll"):
                # explicit rollup marker (the sealer's pre-seal rollup):
                # same exact-position rule for the rolled edge planes
                agg.rollup_now()
            if fused.shape[-1]:
                agg.ingest_fused(
                    np.array(fused),  # frombuffer view is read-only
                    n_spans=meta["n_spans"], n_dur=meta["n_dur"],
                    n_err=meta["n_err"],
                    ts_range=tuple(ts) if (ts := meta.get("ts_range")) else None,
                )
            if "seen" in meta:
                # pre-compaction tallies of a sampled batch: the record
                # holds only kept lanes, so the ingest above under-counted
                # — restore the exact host counters from the meta
                hc = agg.host_counters
                hc["sampledKept"] += meta.get("kept", 0)
                hc["sampledDropped"] += meta["seen"] - meta.get("kept", 0)
                hc["spans"] += meta["seen"] - meta["n_spans"]
                hc["spansWithDuration"] += (
                    meta.get("seen_dur", meta["n_dur"]) - meta["n_dur"]
                )
                hc["spansWithError"] += (
                    meta.get("seen_err", meta["n_err"]) - meta["n_err"]
                )
            agg.wal_seq = seq
            applied += 1
    finally:
        agg.wal_hook = hook
    if applied:
        logger.info("WAL: replayed %d batches (> seq %d)", applied, from_seq)
    return applied
