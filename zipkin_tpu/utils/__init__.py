"""Cross-cutting utilities: component lifecycle, the Call seam, metrics."""
