"""Lazy, retryable unit of (possibly remote) work.

Reference semantics: ``zipkin2/Call.java`` (SURVEY.md §2.1) — every storage
operation returns a lazy call that can run synchronously (``execute()``),
asynchronously (``enqueue(callback)`` / ``await call``), be cloned for retry,
and composed with ``map``/``flat_map``. In this rebuild most in-process work
is cheap, but the seam is kept so the TPU store can hide async device
dispatch, the throttle wrapper can bound concurrency, and callers are
oblivious to which backend they hit.

Idiomatic-Python adjustments vs the Java original:

- a :class:`Call` is awaitable (``await call`` == async execute),
- ``enqueue`` takes plain ``on_success``/``on_error`` callables instead of a
  Callback interface,
- one-shot semantics are enforced exactly as upstream: executing a call twice
  raises; ``clone()`` gives a fresh one.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Any, Callable, Generic, Optional, TypeVar

V = TypeVar("V")
R = TypeVar("R")


class Call(Generic[V]):
    """A lazy computation yielding ``V``. Subclasses implement ``_do_execute``."""

    def __init__(self) -> None:
        self._executed = False
        self._canceled = False
        self._lock = threading.Lock()

    # -- core ------------------------------------------------------------

    def _do_execute(self) -> V:
        raise NotImplementedError

    def _clone_impl(self) -> "Call[V]":
        raise NotImplementedError

    def execute(self) -> V:
        with self._lock:
            if self._executed:
                raise RuntimeError("Call already executed; use clone()")
            self._executed = True
        if self._canceled:
            raise RuntimeError("Call canceled")
        return self._do_execute()

    def enqueue(
        self,
        on_success: Callable[[V], None],
        on_error: Optional[Callable[[BaseException], None]] = None,
    ) -> None:
        """Run and deliver the result to callbacks (synchronously by default;
        wrappers like the throttle or server hand this to an executor)."""
        try:
            result = self.execute()
        except BaseException as e:  # noqa: BLE001 - delivered, not swallowed
            if on_error is not None:
                on_error(e)
            else:
                raise
            return
        on_success(result)

    def __await__(self):
        return asyncio.to_thread(self.execute).__await__()

    def cancel(self) -> None:
        self._canceled = True

    @property
    def canceled(self) -> bool:
        return self._canceled

    def clone(self) -> "Call[V]":
        return self._clone_impl()

    # -- composition -----------------------------------------------------

    def map(self, fn: Callable[[V], R]) -> "Call[R]":
        return _MapCall(self, fn)

    def flat_map(self, fn: Callable[[V], "Call[R]"]) -> "Call[R]":
        return _FlatMapCall(self, fn)

    # -- constructors ----------------------------------------------------

    @staticmethod
    def constant(value: V) -> "Call[V]":
        return _ConstantCall(value)

    @staticmethod
    def emptyList() -> "Call[list]":
        return _ConstantCall([])

    @staticmethod
    def of(fn: Callable[[], V]) -> "Call[V]":
        return _FnCall(fn)


class _ConstantCall(Call[V]):
    def __init__(self, value: V) -> None:
        super().__init__()
        self._value = value

    def _do_execute(self) -> V:
        return self._value

    def _clone_impl(self) -> "Call[V]":
        return _ConstantCall(self._value)


class _FnCall(Call[V]):
    def __init__(self, fn: Callable[[], V]) -> None:
        super().__init__()
        self._fn = fn

    def _do_execute(self) -> V:
        return self._fn()

    def _clone_impl(self) -> "Call[V]":
        return _FnCall(self._fn)


class _MapCall(Call[R]):
    def __init__(self, delegate: Call[V], fn: Callable[[V], R]) -> None:
        super().__init__()
        self._delegate = delegate
        self._fn = fn

    def _do_execute(self) -> R:
        return self._fn(self._delegate.execute())

    def _clone_impl(self) -> "Call[R]":
        return _MapCall(self._delegate.clone(), self._fn)


class _FlatMapCall(Call[R]):
    def __init__(self, delegate: Call[V], fn: Callable[[V], Call[R]]) -> None:
        super().__init__()
        self._delegate = delegate
        self._fn = fn

    def _do_execute(self) -> R:
        return self._fn(self._delegate.execute()).execute()

    def _clone_impl(self) -> "Call[R]":
        return _FlatMapCall(self._delegate.clone(), self._fn)


def aggregate_calls(calls: "list[Call[Any]]") -> Call[None]:
    """Run several calls, surfacing the first error after attempting all.

    Reference: ``zipkin2/internal/AggregateCall.java``.
    """

    class _Aggregate(Call[None]):
        def __init__(self, inner: "list[Call[Any]]") -> None:
            super().__init__()
            self._inner = inner

        def _do_execute(self) -> None:
            first_error: Optional[BaseException] = None
            for c in self._inner:
                try:
                    c.execute()
                except BaseException as e:  # noqa: BLE001
                    if first_error is None:
                        first_error = e
            if first_error is not None:
                raise first_error

        def _clone_impl(self) -> "Call[None]":
            return _Aggregate([c.clone() for c in self._inner])

    return _Aggregate(calls)
