"""Component lifecycle and health contract.

Reference semantics: ``zipkin2/Component.java`` and ``zipkin2/CheckResult.java``
(SURVEY.md §2.1). Everything storage- or collector-shaped participates in the
same lifecycle: a ``check()`` that returns OK or an error (never raises), and
``close()`` for teardown. The server's ``/health`` endpoint aggregates
``check()`` over every registered component.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class CheckResult:
    """Outcome of a health check: OK, or an error with the causing exception."""

    ok: bool
    error: Optional[BaseException] = None

    @staticmethod
    def failed(error: BaseException) -> "CheckResult":
        return CheckResult(ok=False, error=error)

    def __str__(self) -> str:  # pragma: no cover - debug aid
        return "OK" if self.ok else f"FAILED({self.error!r})"


CheckResult.OK = CheckResult(ok=True)  # type: ignore[attr-defined]


class Component:
    """Base for storages, collectors, and other lifecycle'd parts.

    ``check()`` must never raise: implementations catch and wrap failures in a
    failed :class:`CheckResult` so one sick component cannot take down the
    health endpoint.
    """

    def check(self) -> CheckResult:
        return CheckResult.OK  # type: ignore[attr-defined]

    def close(self) -> None:
        """Release resources. Idempotent."""

    def __enter__(self) -> "Component":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
